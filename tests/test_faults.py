"""Availability-axis tests (tier-1 ``faults`` marker, ISSUE 11).

Every failure mode here is provoked deterministically through
:mod:`raft_tpu.testing.faults` and injected clocks — no process kills, no
wall-clock sleeps in assertions:

- the fault registry itself (arming, matching, counting, scoped disarm);
- the write-ahead log (append/replay round trips, torn-tail tolerance,
  batched fsync accounting, sequence continuity across reopen);
- the MutableIndex crash windows (crash between WAL append and memtable
  insert; crash mid-snapshot-save) and the ``load + replay`` recovery
  path, recall-parity-checked against an uncrashed twin;
- ReplicatedShard failover (same-call retry, circuit-breaker fencing,
  backoff re-probes, stale-on-missed-write, whole-or-nothing admission);
- the sharded mesh with replica groups (one dead replica = zero failed
  queries) and the ``/healthz`` replica verdict;
- the client-side bounded retry helper (backoff/jitter policy with an
  injected clock; never retries a spent deadline).
"""

import os
import threading

import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu import stream
from raft_tpu.core.errors import RaftError
from raft_tpu.neighbors import brute_force
from raft_tpu.serve import (DeadlineExceededError, OverloadedError,
                            ReplicaUnavailableError, SearchService,
                            submit_with_retry)
from raft_tpu.stream import (FencingPolicy, MutableIndex, ReplicatedShard,
                             ShardedMutableIndex, WriteAheadLog)
from raft_tpu.stream.wal import WalCorruptError
from raft_tpu.testing import faults

pytestmark = pytest.mark.faults


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


@pytest.fixture(autouse=True)
def _no_leaked_faults():
    """A fault leaked out of any test here must fail THAT test's teardown,
    not poison a sibling suite."""
    yield
    leaked = faults.armed()
    faults.clear()
    assert not leaked, "test left faults armed"


@pytest.fixture
def data(rng):
    return rng.standard_normal((256, 16)).astype(np.float32)


@pytest.fixture
def queries(rng):
    return rng.standard_normal((6, 16)).astype(np.float32)


def bf_build(rows):
    return brute_force.BruteForce().build(jnp.asarray(rows))


def group(data, clock, *, n_replicas=2, policy=None, **kw):
    return ReplicatedShard(
        bf_build(data), n_replicas=n_replicas, delta_capacity=64,
        policy=policy or FencingPolicy(max_consecutive=1, backoff_s=5.0),
        clock=clock, name="g", **kw)


# -- the fault registry ------------------------------------------------------

def test_fire_disarmed_is_noop_and_counts_reset():
    faults.fire("nothing/armed", foo=1)  # must not raise
    with faults.scope():
        faults.inject("p", exc=faults.FaultError("x"), times=1)
        with pytest.raises(faults.FaultError):
            faults.fire("p")
        faults.fire("p")  # times=1 exhausted: no raise
        assert faults.fired("p") == 1
    assert not faults.armed() and faults.fired("p") == 0


def test_after_match_and_callback():
    seen = []
    with faults.scope():
        faults.inject("p", callback=seen.append, after=2,
                      match=lambda ctx: ctx["who"] == "b")
        for who in ("a", "b", "b", "a", "b", "b"):
            faults.fire("p", who=who)
        # 4 matching calls, first 2 skipped by after=2
        assert [c["who"] for c in seen] == ["b", "b"]
        assert seen[0]["point"] == "p"
        assert faults.fired("p") == 2


def test_stacked_injections_fire_in_order():
    with faults.scope():
        order = []
        faults.inject("p", callback=lambda c: order.append(1), times=1)
        faults.inject("p", exc=faults.FaultError("second"))
        with pytest.raises(faults.FaultError):
            faults.fire("p")
        assert order == [1]


# -- WriteAheadLog -----------------------------------------------------------

def test_wal_roundtrip_upsert_delete(tmp_path, rng):
    wal = WriteAheadLog(tmp_path / "w.log", name="t")
    rows = rng.standard_normal((5, 8)).astype(np.float32)
    ids = np.arange(100, 105, dtype=np.int64)
    assert wal.append_upsert(rows, ids) == 1
    assert wal.append_delete([101, 103]) == 2
    wal.close()
    back = list(WriteAheadLog(tmp_path / "w.log", name="t").replay())
    assert [(s, k) for s, k, _, _ in back] == [(1, "upsert"), (2, "delete")]
    np.testing.assert_array_equal(back[0][3], ids)
    np.testing.assert_allclose(back[0][2], rows)
    np.testing.assert_array_equal(back[1][3], [101, 103])


def test_wal_preserves_byte_dtypes(tmp_path, rng):
    wal = WriteAheadLog(tmp_path / "w.log")
    rows = rng.integers(-128, 127, (3, 4), dtype=np.int8)
    wal.append_upsert(rows, np.arange(3))
    (_, _, got, _), = wal.replay()
    assert got.dtype == np.int8
    np.testing.assert_array_equal(got, rows)


def test_wal_seq_continues_across_reopen(tmp_path, rng):
    p = tmp_path / "w.log"
    wal = WriteAheadLog(p)
    wal.append_delete([1])
    wal.append_delete([2])
    wal.close()
    wal2 = WriteAheadLog(p)
    assert wal2.seq == 2
    assert wal2.append_delete([3]) == 3  # numbering never restarts
    assert [s for s, _, _, _ in wal2.replay()] == [1, 2, 3]
    assert [s for s, _, _, _ in wal2.replay(after_seq=2)] == [3]


def test_wal_torn_tail_tolerated_and_truncated(tmp_path, rng):
    p = tmp_path / "w.log"
    wal = WriteAheadLog(p)
    rows = rng.standard_normal((4, 8)).astype(np.float32)
    wal.append_upsert(rows, np.arange(4))
    wal.append_delete([0])
    wal.close()
    good = os.path.getsize(p)
    # a crash mid-append: garbage half-record at the tail
    with open(p, "ab") as f:
        f.write(b"\x01garbage-half-record")
    wal2 = WriteAheadLog(p)
    assert wal2.seq == 2  # torn record never acknowledged
    recs = list(wal2.replay())
    assert len(recs) == 2 and not wal2.last_scan["torn"]  # tail dropped
    assert os.path.getsize(p) == good  # reopen truncated the garbage
    assert wal2.append_delete([1]) == 3  # appends continue past it


def test_wal_strict_replay_raises_on_corruption(tmp_path, rng):
    p = tmp_path / "w.log"
    wal = WriteAheadLog(p)
    wal.append_delete([1])
    wal.append_delete([2])
    wal.close()
    raw = bytearray(p.read_bytes())
    raw[-3] ^= 0xFF  # flip a payload byte of the LAST record
    p.write_bytes(bytes(raw))
    wal2 = WriteAheadLog(p)
    assert [s for s, _, _, _ in wal2.replay()] == [1]  # default: stop there
    with pytest.raises(WalCorruptError):
        list(wal2.replay(strict=True))
    # appending past damage would be unreachable to replay — refused
    with pytest.raises(WalCorruptError):
        wal2.append_delete([3])
    wal2.reset()  # explicit truncation (post-recovery snapshot) clears it
    # the damaged record's seq was never replayable — its number is reused
    assert wal2.append_delete([3]) == 2


def test_wal_fsync_batching(tmp_path, rng):
    wal = WriteAheadLog(tmp_path / "w.log", fsync_every=4)
    with faults.scope():
        faults.inject("wal/fsync", callback=lambda c: None)
        for i in range(8):
            wal.append_delete([i])
        assert faults.fired("wal/fsync") == 2  # 8 appends / 4 per fsync
        wal.append_delete([9])
        wal.flush()  # 1 pending record -> forced sync
        assert faults.fired("wal/fsync") == 3


def test_wal_append_fault_mid_batch(tmp_path, rng):
    """The k-th record of a burst fails: everything before it is durable,
    the failed record was never written."""
    wal = WriteAheadLog(tmp_path / "w.log")
    with faults.scope():
        faults.inject("wal/append", exc=faults.FaultError("disk full"),
                      after=2, times=1)
        wal.append_delete([1])
        wal.append_delete([2])
        with pytest.raises(faults.FaultError):
            wal.append_delete([3])
        wal.append_delete([4])
    assert [s for s, _, _, _ in wal.replay()] == [1, 2, 3]
    # seq 3 was REUSED by the post-failure append (the failed one never
    # hit the file) — replay sees a contiguous, gap-free history
    assert [list(i) for _, _, _, i in wal.replay()] == [[1], [2], [4]]


def test_wal_reset_truncates_but_seq_continues(tmp_path):
    wal = WriteAheadLog(tmp_path / "w.log")
    wal.append_delete([1])
    assert wal.size_bytes > 0
    wal.reset()
    assert wal.size_bytes == 0 and wal.seq == 1
    assert wal.append_delete([2]) == 2
    assert [s for s, _, _, _ in wal.replay()] == [2]


# -- MutableIndex + WAL: the crash windows ----------------------------------

def test_fresh_wrap_refuses_nonempty_wal(tmp_path, data):
    p = tmp_path / "w.log"
    wal = WriteAheadLog(p)
    wal.append_delete([1])
    wal.close()
    with pytest.raises(RaftError, match="already holds records"):
        MutableIndex(bf_build(data), wal=p)


def test_crash_between_wal_and_memtable_recovers(tmp_path, data, queries,
                                                 rng):
    """The tentpole acceptance path: crash after the WAL append but before
    the memtable insert — load + replay recovers every logged write with
    recall parity against an uncrashed twin."""
    snap = str(tmp_path / "snap.bin")
    wpath = str(tmp_path / "wal.log")
    m = MutableIndex(bf_build(data), delta_capacity=64, wal=wpath,
                     snapshot_path=snap)
    stream.save(m, snap)  # baseline snapshot (truncates the empty log)
    rows1 = rng.standard_normal((8, 16)).astype(np.float32)
    rows2 = rng.standard_normal((4, 16)).astype(np.float32)
    m.upsert(rows1)
    m.delete([3, 5, 250])
    with faults.scope():
        faults.inject("stream/post-wal", faults.SimulatedCrash("kill -9"))
        with pytest.raises(faults.SimulatedCrash):
            m.upsert(rows2)
    del m  # the process is gone; only snap + wal.log survive

    twin = MutableIndex(bf_build(data), delta_capacity=64)
    twin.upsert(rows1)
    twin.delete([3, 5, 250])
    twin.upsert(rows2)  # the logged write replays, so the twin applies it

    rec = stream.load(snap, wal=wpath)
    assert rec.last_recovery == {"replayed": 3, "skipped": 0,
                                 "torn": False, "wal_seq": 3}
    dr, ir = rec.search(queries, 10)
    dt, it = twin.search(queries, 10)
    np.testing.assert_array_equal(np.asarray(ir), np.asarray(it))
    np.testing.assert_allclose(np.asarray(dr), np.asarray(dt), rtol=1e-5)
    assert rec.size == twin.size
    # the log re-attached: new writes are durable and replayable
    rec.upsert(rng.standard_normal((2, 16)).astype(np.float32))
    assert rec._wal.seq == 4


def test_snapshot_covers_log_and_replay_skips(tmp_path, data, rng):
    snap = str(tmp_path / "snap.bin")
    wpath = str(tmp_path / "wal.log")
    m = MutableIndex(bf_build(data), delta_capacity=64, wal=wpath)
    m.upsert(rng.standard_normal((4, 16)).astype(np.float32))
    stream.save(m, snap)  # snapshot covers seq 1; log truncates
    assert m._wal.size_bytes == 0
    m.delete([0])  # seq 2, only in the log
    rec = stream.load(snap, wal=wpath)
    # only the post-snapshot record replays
    assert rec.last_recovery["replayed"] == 1
    assert rec.last_recovery["wal_seq"] == 2
    assert rec.size == m.size


def test_compaction_swap_truncates_wal(tmp_path, data, rng):
    snap = str(tmp_path / "snap.bin")
    wpath = str(tmp_path / "wal.log")
    m = MutableIndex(bf_build(data), delta_capacity=64, wal=wpath,
                     snapshot_path=snap)
    m.upsert(rng.standard_normal((8, 16)).astype(np.float32))
    assert m._wal.size_bytes > 0
    report = m.compact()
    assert report["snapshot"] == snap
    assert m._wal.size_bytes == 0  # the snapshot now covers the log
    rec = stream.load(snap, wal=wpath)
    assert rec.last_recovery["replayed"] == 0
    assert rec.size == m.size


def test_crashed_save_keeps_previous_snapshot(tmp_path, data, queries, rng):
    """Satellite: a fault-injected crash mid-save (after the temp write,
    before the rename) leaves the previous snapshot readable AND the WAL
    untruncated — nothing acknowledged is lost."""
    snap = str(tmp_path / "snap.bin")
    wpath = str(tmp_path / "wal.log")
    m = MutableIndex(bf_build(data), delta_capacity=64, wal=wpath)
    stream.save(m, snap)
    m.upsert(rng.standard_normal((4, 16)).astype(np.float32))
    before = m.search(queries, 10)
    with faults.scope():
        faults.inject("serialize/atomic-write",
                      faults.SimulatedCrash("kill -9"))
        with pytest.raises(faults.SimulatedCrash):
            stream.save(m, snap)
    assert m._wal.size_bytes > 0  # crash BEFORE rename: log kept
    rec = stream.load(snap, wal=wpath)  # previous snapshot + full replay
    assert rec.last_recovery["replayed"] == 1
    got = rec.search(queries, 10)
    np.testing.assert_array_equal(np.asarray(got[1]),
                                  np.asarray(before[1]))
    assert not any(f.startswith("snap.bin.tmp")
                   for f in os.listdir(tmp_path))  # temp cleaned up


def test_plain_index_save_is_atomic(tmp_path, data):
    """Satellite: the sealed-index save paths ride atomic_write too — a
    crashed save leaves the previous file loadable."""
    p = str(tmp_path / "bf.bin")
    idx = bf_build(data)
    brute_force.save(idx, p)
    with faults.scope():
        faults.inject("serialize/atomic-write",
                      faults.SimulatedCrash("kill -9"))
        with pytest.raises(faults.SimulatedCrash):
            brute_force.save(bf_build(data[:32]), p)
    back = brute_force.load(p)
    assert back.dataset.shape == (data.shape[0], data.shape[1])


# -- ReplicatedShard: failover ----------------------------------------------

def test_replicas_lockstep_and_r1_parity(data, queries, rng):
    clock = FakeClock()
    g = group(data, clock)
    single = MutableIndex(bf_build(data), delta_capacity=64)
    rows = rng.standard_normal((8, 16)).astype(np.float32)
    g.upsert(rows)
    single.upsert(rows)
    g.delete([1, 2])
    single.delete([1, 2])
    assert [r.size for r in g.replicas] == [single.size, single.size]
    dg, ig = g.search(queries, 10)
    ds, is_ = single.search(queries, 10)
    np.testing.assert_array_equal(np.asarray(ig), np.asarray(is_))
    np.testing.assert_allclose(np.asarray(dg), np.asarray(ds), rtol=1e-6)


def test_read_failover_same_call(data, queries):
    clock = FakeClock()
    g = group(data, clock)
    want = np.asarray(g.search(queries, 5)[1])
    with faults.scope():
        faults.inject("replica/search", exc=faults.FaultError("dead"),
                      match=lambda c: c["replica"].endswith("/r0"))
        got = np.asarray(g.search(queries, 5)[1])  # must not raise
        assert faults.fired("replica/search") >= 1
    np.testing.assert_array_equal(got, want)
    h = g.health()
    r0 = next(r for r in h["replicas"] if r["replica"].endswith("/r0"))
    assert r0["fenced"] and not r0["stale"]
    assert "FaultError" in r0["last_error"]


def test_breaker_opens_after_consecutive_strikes(data, queries):
    clock = FakeClock()
    g = group(data, clock,
              policy=FencingPolicy(max_consecutive=2, backoff_s=5.0))
    with faults.scope():
        faults.inject("replica/search", exc=faults.FaultError("dead"),
                      match=lambda c: c["replica"].endswith("/r0"))
        # r0 is struck at most once per search (the failover moves on);
        # the breaker stays closed until max_consecutive strikes accrue
        while g._health[0].consecutive < 1:
            g.search(queries, 5)
        assert g.health()["healthy"] == 2  # one strike: not fenced yet
        while g._health[0].consecutive < 2:
            g.search(queries, 5)
        assert g.health()["healthy"] == 1  # breaker open
        # fenced r0 is not picked at all now — no new fires
        n = faults.fired("replica/search")
        g.search(queries, 5)
        assert faults.fired("replica/search") == n


def test_probe_heals_and_failed_probe_doubles_backoff(data, queries):
    clock = FakeClock()
    g = group(data, clock)  # max_consecutive=1, backoff 5s
    with faults.scope():
        faults.inject("replica/search", exc=faults.FaultError("dead"),
                      match=lambda c: c["replica"].endswith("/r0"))
        while g._health[0].fenced_until is None:
            g.search(queries, 5)  # strike fences r0 until t=5
        assert g._health[0].fenced_until == pytest.approx(5.0)
        clock.advance(6.0)  # half-open: the NEXT pick probes r0 first
        g.search(queries, 5)  # probe fails -> re-fence, doubled backoff
        assert g._health[0].fenced_until == pytest.approx(6.0 + 10.0)
    clock.advance(11.0)  # past the doubled fence; fault cleared: probe ok
    g.search(queries, 5)
    assert g.health()["healthy"] == 2
    assert g._health[0].backoff == 5.0  # success re-bases the backoff


def test_wedged_replica_slow_strike_no_wall_sleep(data, queries):
    """A hang is simulated by a callback advancing the injected clock past
    the deadline: the scan 'takes' 10s, the result is still returned
    (valid), and the breaker fences the replica for future picks."""
    clock = FakeClock()
    g = group(data, clock,
              policy=FencingPolicy(deadline_s=0.5, max_consecutive=1,
                                   backoff_s=5.0))
    want = np.asarray(g.search(queries, 5)[1])
    with faults.scope():
        # whichever replica the pick lands on 'hangs': the injected clock
        # jumps past deadline_s during its scan — no wall sleep anywhere
        faults.inject("replica/search",
                      callback=lambda c: clock.advance(10.0), times=1)
        got = np.asarray(g.search(queries, 5)[1])
    np.testing.assert_array_equal(got, want)  # the slow result is valid
    h = g.health()
    assert sum(1 for r in h["replicas"] if r["fenced"]) == 1


def test_write_failure_marks_stale_not_lost(data, queries, rng):
    clock = FakeClock()
    g = group(data, clock)
    rows = rng.standard_normal((4, 16)).astype(np.float32)
    with faults.scope():
        faults.inject("replica/upsert", exc=faults.FaultError("dev fault"),
                      match=lambda c: c["replica"].endswith("/r1"),
                      times=1)
        gids = g.upsert(rows)  # succeeds: r0 applied it
    assert g.stats()["stale"] == 1
    assert g.replicas[0].size == data.shape[0] + 4
    # reads NEVER touch the stale twin (it would un-acknowledge the write)
    _, ids = g.search(rows[:1], 1)
    assert int(np.asarray(ids)[0, 0]) == int(gids[0])
    # later writes skip the stale twin instead of diverging it further
    g.upsert(rng.standard_normal((2, 16)).astype(np.float32))
    assert g.replicas[0].size == g.replicas[1].size + 6
    clock.advance(100.0)  # stale is permanent: backoff cannot heal it
    assert g.stats()["stale"] == 1 and g.stats()["healthy"] == 1


def test_all_replicas_out_raises_structured(data, queries):
    clock = FakeClock()
    g = group(data, clock)
    with faults.scope():
        faults.inject("replica/search", exc=faults.FaultError("dead"))
        with pytest.raises(ReplicaUnavailableError) as ei:
            g.search(queries, 5)
    assert ei.value.name == "g" and ei.value.replicas == 2
    assert ei.value.fenced == 2
    assert isinstance(ei.value.__cause__, faults.FaultError)
    # both fenced now; past the backoff the group heals
    clock.advance(6.0)
    assert np.asarray(g.search(queries, 5)[0]).shape == (6, 5)


def test_group_admission_whole_or_nothing(data, rng):
    clock = FakeClock()
    g = ReplicatedShard(bf_build(data), n_replicas=2, delta_capacity=8,
                        clock=clock, name="g")
    g.upsert(rng.standard_normal((6, 16)).astype(np.float32))
    with pytest.raises(stream.DeltaFullError):
        g.upsert(rng.standard_normal((4, 16)).astype(np.float32))
    # nothing landed anywhere — both twins still at 6 delta rows
    assert [r.stats()["delta_rows"] for r in g.replicas] == [6, 6]


def test_all_stale_group_refuses_writes(data, rng):
    """With EVERY twin stale a write must refuse loudly — acknowledging
    it with no twin (and no WAL record) to hold it would lose it
    silently."""
    clock = FakeClock()
    g = group(data, clock)
    rows = rng.standard_normal((4, 16)).astype(np.float32)
    with faults.scope():
        faults.inject("replica/upsert", exc=faults.FaultError("dev fault"))
        with pytest.raises(faults.FaultError):
            g.upsert(rows)  # all twins fail -> both stale, write raises
    assert g.stats()["stale"] == 2
    with pytest.raises(ReplicaUnavailableError):
        g.upsert(rows)
    with pytest.raises(ReplicaUnavailableError):
        g.delete([0, 1])


def test_failed_group_write_rolls_back_wal(tmp_path, data, rng):
    """A write that failed on EVERY twin raised to the caller — its WAL
    record must not survive to resurrect the write at recovery."""
    clock = FakeClock()
    snap = str(tmp_path / "snap.bin")
    wpath = str(tmp_path / "wal.log")
    g = group(data, clock, wal=wpath, snapshot_path=snap)
    g.save(snap)
    g.upsert(rng.standard_normal((4, 16)).astype(np.float32))
    seq_before, size_before = g._wal.seq, g._wal.size_bytes
    with faults.scope():
        faults.inject("replica/upsert", exc=faults.FaultError("dev fault"))
        with pytest.raises(faults.FaultError):
            g.upsert(rng.standard_normal((4, 16)).astype(np.float32))
    assert g._wal.seq == seq_before
    assert g._wal.size_bytes == size_before
    rec = stream.load(snap, wal=wpath)
    assert rec.last_recovery["replayed"] == 1  # the acknowledged write only
    assert rec.size == data.shape[0] + 4


def test_validation_error_does_not_strike(data, queries):
    """A deterministic client error (bad query dim) must raise without
    striking the breaker — a few malformed requests must never fence the
    whole group and fail subsequent VALID queries."""
    clock = FakeClock()
    g = group(data, clock)
    bad = np.zeros((3, 7), np.float32)  # wrong dim (16 expected)
    for _ in range(3):
        with pytest.raises(Exception) as ei:
            g.search(bad, 5)
        assert not isinstance(ei.value, ReplicaUnavailableError)
    h = g.health()
    assert all(r["strikes_total"] == 0 and not r["fenced"]
               for r in h["replicas"]), h
    assert np.asarray(g.search(queries, 5)[0]).shape == (6, 5)


def test_replica_devices_must_not_collide(data):
    """devices= with fewer devices than replicas would co-locate twins of
    one shard — silently voiding the anti-affinity the groups promise."""
    import jax

    with pytest.raises(RaftError, match="anti-affinity"):
        ShardedMutableIndex(data, n_shards=2, build=bf_build, replicas=3,
                            delta_capacity=64,
                            devices=jax.devices()[:2])


def test_group_wal_durability(tmp_path, data, queries, rng):
    """Group-level WAL: the log is written once for the group; recovery is
    a degraded-to-one stream.load that holds every acknowledged write."""
    clock = FakeClock()
    snap = str(tmp_path / "snap.bin")
    wpath = str(tmp_path / "wal.log")
    g = group(data, clock, wal=wpath, snapshot_path=snap)
    g.save(snap)
    rows = rng.standard_normal((8, 16)).astype(np.float32)
    gids = g.upsert(rows)
    g.delete(gids[:2].tolist())
    rec = stream.load(snap, wal=wpath)
    assert rec.last_recovery["replayed"] == 2
    assert rec.size == g.size
    dr, ir = rec.search(queries, 10)
    dg, ig = g.search(queries, 10)
    np.testing.assert_array_equal(np.asarray(ir), np.asarray(ig))


def test_group_save_truncates_and_compact_snapshots(tmp_path, data, rng):
    clock = FakeClock()
    snap = str(tmp_path / "snap.bin")
    wpath = str(tmp_path / "wal.log")
    g = group(data, clock, wal=wpath, snapshot_path=snap)
    g.upsert(rng.standard_normal((4, 16)).astype(np.float32))
    assert g._wal.size_bytes > 0
    report = g.compact()
    assert report["snapshot"] == snap and len(report["replica_wall_s"]) == 2
    assert g._wal.size_bytes == 0
    rec = stream.load(snap, wal=wpath)
    assert rec.last_recovery["replayed"] == 0 and rec.size == g.size


# -- sharded mesh with replica groups ---------------------------------------

def test_mesh_replica_parity_and_one_dead_replica(data, queries, rng):
    clock = FakeClock()
    sm = ShardedMutableIndex(
        data, n_shards=3, build=bf_build, replicas=2, delta_capacity=64,
        fencing=FencingPolicy(max_consecutive=1, backoff_s=5.0),
        clock=clock, name="mesh")
    plain = ShardedMutableIndex(data, n_shards=3, build=bf_build,
                                delta_capacity=64, name="plainmesh")
    rows = rng.standard_normal((12, 16)).astype(np.float32)
    sm.upsert(rows)
    plain.upsert(rows)
    sm.delete([3, 7])
    plain.delete([3, 7])
    want = np.asarray(plain.search(queries, 10)[1])
    np.testing.assert_array_equal(np.asarray(sm.search(queries, 10)[1]),
                                  want)
    with faults.scope():
        # kill shard 1's replica 0 outright: EVERY query must still answer
        faults.inject("replica/search", exc=faults.FaultError("dead"),
                      match=lambda c: c["replica"] == "mesh/shard1/r0")
        for _ in range(4):
            got = np.asarray(sm.search(queries, 10)[1])
            np.testing.assert_array_equal(got, want)
    h = sm.health()
    assert h["healthy_min"] >= 1
    st = sm.stats()
    assert st["replicas"] == 6 and st["shards"] == 3


def test_mesh_staggered_compact_with_replicas(data, rng, queries):
    clock = FakeClock()
    sm = ShardedMutableIndex(data, n_shards=2, build=bf_build, replicas=2,
                             delta_capacity=32, clock=clock, name="m2")
    sm.upsert(rng.standard_normal((8, 16)).astype(np.float32))
    report = sm.compact()
    assert "shard" in report and len(report["replica_wall_s"]) == 2
    assert np.asarray(sm.search(queries, 10)[0]).shape == (6, 10)


def test_mesh_hook_serves_through_failover(data, queries, rng):
    clock = FakeClock()
    sm = ShardedMutableIndex(
        data, n_shards=2, build=bf_build, replicas=2, delta_capacity=64,
        fencing=FencingPolicy(max_consecutive=1, backoff_s=5.0),
        clock=clock, name="hookmesh")
    hook = sm.searcher()
    want = np.asarray(hook(queries, 10)[1])
    with faults.scope():
        faults.inject("replica/search", exc=faults.FaultError("dead"),
                      match=lambda c: c["replica"].endswith("shard0/r0"))
        got = np.asarray(hook(queries, 10)[1])  # issued BEFORE the fence
    np.testing.assert_array_equal(got, want)


# -- /healthz replica verdict ------------------------------------------------

def test_healthz_folds_replica_health(data, queries):
    from raft_tpu.obs.http import _fold_replica_health

    clock = FakeClock()
    g = group(data, clock)
    code, body = _fold_replica_health(200, {"status": "ready"}, g.health())
    assert (code, body["status"]) == (200, "ready")
    with faults.scope():
        faults.inject("replica/search", exc=faults.FaultError("dead"),
                      match=lambda c: c["replica"].endswith("/r0"))
        while g._health[0].fenced_until is None:
            g.search(queries, 5)
    code, body = _fold_replica_health(200, {"status": "ready"}, g.health())
    assert (code, body["status"]) == (200, "degraded")  # capacity down
    # a failing SLO verdict is never upgraded by healthy replicas
    code, body = _fold_replica_health(503, {"status": "failing"},
                                      g.health())
    assert (code, body["status"]) == (503, "failing")
    with faults.scope():
        faults.inject("replica/search", exc=faults.FaultError("dead"))
        with pytest.raises(ReplicaUnavailableError):
            g.search(queries, 5)
    code, body = _fold_replica_health(200, {"status": "ready"}, g.health())
    assert (code, body["status"]) == (503, "failing")  # zero pickable


def test_healthz_endpoint_serves_replica_detail(data):
    from raft_tpu.obs.http import MetricsExporter
    from urllib.request import urlopen
    import json

    clock = FakeClock()
    g = group(data, clock)
    with MetricsExporter(port=0, replicas=g) as exp:
        raw = urlopen(f"http://127.0.0.1:{exp.port}/healthz",
                      timeout=5).read()
    body = json.loads(raw)
    assert body["status"] == "ready"
    assert [r["fenced"] for r in body["replicas"]["replicas"]] == \
        [False, False]


# -- submit_with_retry -------------------------------------------------------

class _ScriptedService:
    """Raises the scripted errors in order, then admits."""

    def __init__(self, script):
        self.script = list(script)
        self.calls = []

    def submit(self, name, queries, k, timeout_s=None):
        self.calls.append(timeout_s)
        if self.script:
            err = self.script.pop(0)
            if err is not None:
                raise err
        return "future"


def test_retry_backs_off_then_admits():
    clock, sleeps = FakeClock(), []

    def sleep(dt):
        sleeps.append(dt)
        clock.advance(dt)

    svc = _ScriptedService([OverloadedError("full"), OverloadedError("full"),
                            None])
    rng = __import__("random").Random(7)
    fut = submit_with_retry(svc, "main", None, 5, base_s=0.01, jitter=0.5,
                            clock=clock, sleep=sleep, rng=rng)
    assert fut == "future" and len(svc.calls) == 3
    # exponential base with +-50% jitter: sleep n in [cap/2, 3cap/2]
    assert 0.005 <= sleeps[0] <= 0.015
    assert 0.01 <= sleeps[1] <= 0.03


def test_retry_never_retries_deadline():
    svc = _ScriptedService([DeadlineExceededError("late"), None])
    with pytest.raises(DeadlineExceededError):
        submit_with_retry(svc, "main", None, 5, sleep=lambda dt: None)
    assert len(svc.calls) == 1


def test_retry_exhausts_with_last_refusal():
    svc = _ScriptedService([OverloadedError(f"full {i}") for i in range(9)])
    with pytest.raises(OverloadedError, match="full 2"):
        submit_with_retry(svc, "main", None, 5, max_attempts=3,
                          sleep=lambda dt: None)
    assert len(svc.calls) == 3


def test_retry_respects_deadline_budget():
    clock = FakeClock()

    def sleep(dt):
        clock.advance(dt)

    # backoff would cross the deadline: DeadlineExceeded WITHOUT sleeping
    svc = _ScriptedService([OverloadedError("full")] * 5)
    with pytest.raises(DeadlineExceededError):
        submit_with_retry(svc, "main", None, 5, timeout_s=0.001,
                          base_s=1.0, jitter=0.0, clock=clock, sleep=sleep)
    assert clock.t == 0.0  # never slept into the spent budget
    assert len(svc.calls) == 1
    # remaining budget shrinks across attempts
    svc2 = _ScriptedService([OverloadedError("full"), None])
    submit_with_retry(svc2, "main", None, 5, timeout_s=10.0, base_s=0.5,
                      jitter=0.0, clock=clock, sleep=sleep)
    assert svc2.calls[0] == pytest.approx(10.0)
    assert svc2.calls[1] == pytest.approx(9.5)


def test_retry_against_real_service(data):
    """End-to-end: a 1-slot queue refuses the second submit; the retry
    admits it after the first flush drains (injected clock, pump-driven)."""
    clock = FakeClock()
    svc = SearchService(max_batch=2, max_wait_us=1.0, max_queue_rows=2,
                        clock=clock, start_workers=False)
    svc.publish("main", bf_build(data), k=5, warm=False)
    q = data[:2]
    f1 = svc.submit("main", q, 5)

    def sleep(dt):
        clock.advance(dt)
        svc.pump()  # the drain that clears the overload

    f2 = submit_with_retry(svc, "main", q, 5, base_s=0.001,
                           clock=clock, sleep=sleep)
    clock.advance(1.0)
    svc.pump()
    assert f1.result(timeout=0)[0].shape == (2, 5)
    assert f2.result(timeout=0)[0].shape == (2, 5)
    svc.shutdown()
