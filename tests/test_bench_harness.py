"""ANN bench harness smoke test (bench/ann/run.py).

Analogue of the reference harness's CI smoke coverage: a tiny synthetic
config must build, search, compute recall, and emit the CSV.
"""

import csv
import json
import os
import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parents[1]


def test_harness_end_to_end(tmp_path):
    conf_dir = tmp_path / "conf"
    conf_dir.mkdir()
    conf = {
        "dataset": {
            "name": "tiny",
            "synthetic": {"n": 2000, "dim": 16, "n_queries": 100, "seed": 0},
            "distance": "euclidean",
        },
        "search_basic_param": {"batch_size": 100, "k": 5, "run_count": 1},
        "index": [
            {"name": "bf", "algo": "raft_tpu.brute_force", "build_param": {},
             "search_params": [{}]},
            {"name": "ivf", "algo": "raft_tpu.ivf_flat",
             "build_param": {"n_lists": 8},
             "search_params": [{"n_probes": 8}]},
        ],
    }
    (conf_dir / "tiny.json").write_text(json.dumps(conf))

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, str(REPO / "bench/ann/run.py"),
         "--conf", str(conf_dir / "tiny.json"), "--build", "--search"],
        capture_output=True, text=True, env=env, timeout=600,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]

    out_csv = tmp_path / "results" / "tiny.csv"
    assert out_csv.exists(), proc.stdout
    rows = list(csv.DictReader(open(out_csv)))
    assert len(rows) == 2
    by_name = {r["name"]: r for r in rows}
    # brute force IS the ground truth → recall 1.0
    assert float(by_name["bf"]["recall@5"]) == 1.0
    # probing all 8 lists is exhaustive → recall 1.0
    assert float(by_name["ivf"]["recall@5"]) > 0.99
    assert float(by_name["bf"]["qps"]) > 0


def test_row_guard_hang_converts_to_labeled_row():
    """A row body that hangs past the watchdog deadline (the observed
    mid-build tunnel failure mode) must convert into a labeled error row plus
    an exit-0 request — not rely on the driver's external kill."""
    import threading

    import bench

    rows = []
    exit_codes = []
    ev = threading.Event()
    bench._row_guard(rows, "hang_row", ev.wait, timeout_s=0.2,
                     _exit=exit_codes.append)
    ev.set()  # release the stuck daemon thread
    assert exit_codes == [0]
    assert rows and rows[0]["name"] == "hang_row"
    assert "watchdog" in rows[0]["error"]


def test_row_guard_success_and_error_paths():
    import bench

    rows = []
    bench._row_guard(rows, "ok_row", lambda: None, timeout_s=5)
    assert rows == []

    def boom():
        raise ValueError("boom")

    bench._row_guard(rows, "err_row", boom, timeout_s=5)
    assert rows[0]["name"] == "err_row"
    assert rows[0]["error"].startswith("ValueError")


def test_no_metrics_flag_disables_obs(monkeypatch):
    """`bench.py --no-metrics` must switch the whole obs surface off (the
    disabled-path proof ISSUE 2 asks for): rows then carry no "obs" field
    and the emitted snapshot says metrics_enabled=false."""
    import bench
    from raft_tpu import obs

    called = {}
    monkeypatch.setattr(bench, "_run",
                        lambda rows: called.setdefault("ran", True))
    try:
        rc = bench.main(["--no-metrics"])
        assert rc == 0 and called["ran"]
        assert bench._STATE["metrics"] is False
        assert not obs.enabled()
    finally:
        obs.enable()
        bench._STATE["metrics"] = True


def test_flagship_i8_row_smoke(monkeypatch):
    """The driver-bench i8 rows (this PR's acceptance measurement) must
    produce qps+recall rows, not guarded error rows, when the kernels run —
    a NameError in the row body would silently erase the headline number on
    the TPU driver run. Shrunk shapes, interpret-mode kernels; the shape
    arguments exist on the row functions exactly for this smoke."""
    monkeypatch.setenv("RAFT_TPU_FUSED_KNN_INTERPRET", "1")
    import bench

    rows = []
    bench._flagship_exact(rows, n=4500, d=72, m=150, k=10, n_batches=2)
    by = {r["name"]: r for r in rows if "name" in r}
    assert "exact_fused_knn_100k" in by, rows
    row = by.get("exact_fused_knn_100k_i8")
    assert row is not None and "error" not in row, rows
    # uniform [0,1) quantized onto 1/255 bins: neighbor margins at this
    # scale dwarf the quantization noise
    assert row["recall"] > 0.8, row
    assert row["i8_over_f32"] > 0, row
    assert by["exact_xla_control"]["fused_over_control"] > 0, by


def test_ivf_pq_i8_row_smoke(monkeypatch):
    monkeypatch.setenv("RAFT_TPU_FUSED_KNN_INTERPRET", "1")
    import numpy as np

    import bench

    rng = np.random.default_rng(3)
    centers = rng.random((32, 64)).astype(np.float32) * 10.0
    lab = rng.integers(0, 32, 6000)
    dataset = (centers[lab]
               + 0.3 * rng.standard_normal((6000, 64))).astype(np.float32)
    qsets = []
    for _ in range(3):
        qlab = rng.integers(0, 32, 200)
        qsets.append((centers[qlab] + 0.3 * rng.standard_normal(
            (200, 64))).astype(np.float32))
    import jax.numpy as jnp

    rows = []
    bench._row_ivf_pq_i8(rows, jnp.asarray(dataset),
                         [jnp.asarray(q) for q in qsets],
                         n_lists=32, pq_dim=32)
    row = rows[-1]
    assert row["name"] == "ivf_pq_1m_i8" and "error" not in row, rows
    assert row["recall"] > 0.7, row
    assert row["i8_over_f32"] is None  # no f32 LID row in this smoke


def test_serve_row_smoke(monkeypatch):
    """The --serve bench row (ISSUE 3 acceptance measurement) must produce
    a full row — qps, ratio, latency percentiles, occupancy, and the
    zero-loss zero-cold-compile swap proof — not a guarded error row.
    Shrunk shapes; the real protocol runs on the TPU driver."""
    import pytest

    pytest.importorskip("jax")
    import bench

    rows = []
    bench._row_serve(rows, n=3000, d=32, n_lists=16, pq_dim=16, k=5,
                     n_probes=16, threads=3, per_thread=30, seq_queries=24,
                     max_batch=8, max_wait_us=500.0, ncl=32)
    row = rows[-1]
    assert row["name"] == "serve_ivf_pq_100k" and "error" not in row, rows
    assert row["swap"]["failed"] == 0, row
    assert row["swap"]["version"] == 2, row
    # the swap window must not cold-compile: every serving program was
    # warmed at publish and the rebuilt index is HLO-identical
    assert row["swap"]["compile_s"] == 0.0, row
    assert row["swap"]["cache_misses"] == 0, row
    assert row["qps"] > 0 and row["seq_qps"] > 0, row
    assert row["p99_ms"] >= row["p50_ms"] > 0, row
    assert 0 < row["mean_batch_occupancy"] <= 1.0, row
    assert row["recall"] > 0.5, row


def test_serve_flag_runs_only_the_serve_row(monkeypatch):
    """`bench.py --serve` is the parameter-iteration loop: setup + the serve
    row, nothing else."""
    import bench

    calls = []
    monkeypatch.setattr(bench, "_setup", lambda rows: calls.append("setup"))
    monkeypatch.setattr(
        bench, "_row_serve",
        lambda rows: rows.append({"name": "serve_ivf_pq_100k", "qps": 1.0}))
    monkeypatch.setattr(bench, "_run",
                        lambda rows: calls.append("run"))  # must NOT fire
    try:
        rc = bench.main(["--serve"])
        assert rc == 0 and calls == ["setup"]
        assert any(r.get("name") == "serve_ivf_pq_100k"
                   for r in bench._STATE["rows"])
    finally:
        bench._STATE["rows"].clear()


def test_serve_pipeline_row_smoke(monkeypatch):
    """The --serve-pipeline A/B row (ISSUE 12 acceptance measurement) must
    produce a full row — both modes' per-flush QPS, latency percentiles and
    recall, the queue-wait vs flush decomposition, the dispatch meter, the
    zero-loss/zero-cold-compile proof, and the flat staging-ledger wave
    levels — not a guarded error row. Shrunk shapes; the real A/B runs on
    the TPU driver."""
    import pytest

    pytest.importorskip("jax")
    import bench

    rows = []
    bench._row_serve_pipeline(rows, n=3000, d=32, n_lists=16, pq_dim=16,
                              k=5, n_probes=16, threads=3, per_thread=30,
                              max_batch=8, max_wait_us=500.0, ncl=32,
                              depth=2, waves=2)
    row = rows[-1]
    assert row["name"] == "serve_pipeline_100k" and "error" not in row, rows
    # zero failed queries, both modes
    assert row["failed"] == 0, row
    assert row["qps"] > 0 and row["sync_qps"] > 0, row
    assert row["p99_ms"] >= row["p50_ms"] > 0, row
    assert row["sync_p99_ms"] >= row["sync_p50_ms"] > 0, row
    # identical recall: same index, same query pool, both modes measured
    assert row["recall"] > 0.5, row
    assert row["recall"] == pytest.approx(row["sync_recall"], abs=0.02), row
    # the latency decomposition is present for BOTH modes (where a win
    # lands must be readable from the artifact)
    for mode in ("sync", "pipelined"):
        assert row["decomp"][mode]["queue_wait_ms_mean"] >= 0, row
        assert row["decomp"][mode]["flush_ms_mean"] > 0, row
    # the dispatch meter records only in pipelined mode
    assert row["dispatches_per_flush_mean"] >= 1, row
    # zero cold compiles across the pipelined loaded window: publish
    # warmed the ladder, the committed placements, and the stage programs
    assert row["pipeline"]["compile_s"] == 0.0, row
    assert row["pipeline"]["cache_misses"] == 0, row
    assert row["pipeline"]["staging_warmed"] == 4, row  # buckets 1,2,4,8
    # staging: the accounted ledger bytes are FLAT across the post-load
    # waves while donation_frees ADVANCES every wave — the previous query
    # buffer is actually deleted per donated upload (no growth across
    # cycles; a backend ignoring donate_argnums would flatline the frees)
    st = row["staging"]
    assert st["pinned"] and st["uploads"] > 0, row
    assert st["donation_frees"] >= 1, row
    ws = st["by_wave"]
    assert len(ws) == 2, row
    ledger = [w["ledger_bytes"] for w in ws]
    assert -1 not in ledger and len(set(ledger)) == 1, row
    frees = [w["donation_frees"] for w in ws]
    assert frees[1] > frees[0] >= 1, row


def test_serve_pipeline_flag_runs_only_the_pipeline_row(monkeypatch):
    """`bench.py --serve-pipeline` is the pipeline-parameter iteration
    loop: setup + the pipeline A/B row, nothing else."""
    import bench

    calls = []
    monkeypatch.setattr(bench, "_setup", lambda rows: calls.append("setup"))
    monkeypatch.setattr(
        bench, "_row_serve_pipeline",
        lambda rows: rows.append({"name": "serve_pipeline_100k",
                                  "qps": 1.0, "recall": 1.0}))
    monkeypatch.setattr(bench, "_run",
                        lambda rows: calls.append("run"))  # must NOT fire
    try:
        rc = bench.main(["--serve-pipeline"])
        assert rc == 0 and calls == ["setup"]
        assert any(r.get("name") == "serve_pipeline_100k"
                   for r in bench._STATE["rows"])
    finally:
        bench._STATE["rows"].clear()


def test_render_note_quotes_the_artifact():
    """bench.py --note regenerates the BASELINE round-note table FROM the
    committed artifact (VERDICT r5 #7: the r05 note described a different
    session than BENCH_r05.json) — every number in the output must be a
    number from the artifact, ratios included, with the driver wrapper
    ({rc, tail, parsed}) unwrapped."""
    import bench

    artifact = {
        "rc": 0, "tail": "...",
        "parsed": {
            "metric": "exact brute-force kNN QPS", "value": 192111.3,
            "unit": "QPS", "vs_baseline": 1.734, "elapsed_s": 194.4,
            "rows": [
                {"name": "exact_fused_knn_100k", "qps": 192111.3,
                 "recall": 1.0, "build_s": 0.0},
                {"name": "exact_xla_control", "qps": 137586.3, "recall": 1.0,
                 "build_s": 0.0, "fused_over_control": 1.396},
                {"name": "cagra_1m_itopk32", "qps": 35879.4,
                 "recall": 0.9714, "build_s": 135.6},
                {"name": "ivf_pq_1m_i8", "qps": 30000.0, "recall": 0.97,
                 "build_s": 5.0, "i8_over_f32": 0.87},
                {"name": "broken_row", "error": "TPU fell over"},
            ],
        },
    }
    note = bench._render_note(artifact)
    for needle in ("192,111.3", "137,586.3", "1.396", "35,879.4", "0.9714",
                   "135.6", "i8/f32 **0.87**", "fused/control **1.396**",
                   "vs_baseline 1.734", "broken_row | ERROR",
                   "TPU fell over"):
        assert needle in note, (needle, note)
    # regression guard: the r05 drift was prose saying 162.8k/148.3k/1.098
    for stale in ("162", "148,3", "1.098"):
        assert stale not in note


def test_serve_churn_row_smoke():
    """The --serve-churn bench row (ISSUE 5 acceptance measurement) must
    produce a full row: search qps + latency percentiles, write throughput,
    >= 2 compaction swaps with zero failed queries, mid-churn recall
    bookkeeping, and the rehearsal-backed zero-cold-compile proof. Shrunk
    shapes (toy PQ quantization — the recall PARITY bar applies at driver
    scale; here the gap bound is loose), real protocol on the TPU driver."""
    import pytest

    pytest.importorskip("jax")
    import bench

    rows = []
    bench._row_serve_churn(rows, n=2500, d=32, n_lists=16, pq_dim=32, k=5,
                           n_probes=32, threads=3, writer_steps=12,
                           upserts_per_step=24, deletes_per_step=8,
                           delta_capacity=128, compact_fill=0.75,
                           max_batch=8, max_wait_us=500.0, ncl=32, n_eval=64)
    row = rows[-1]
    assert row["name"] == "serve_churn_ivf_pq_100k" and "error" not in row, rows
    assert row["churn"]["failed"] == 0, row
    assert row["churn"]["compactions"] >= 2, row
    # zero cold compiles across the whole loaded window — folds, publish
    # warms, flips and every flush (the rehearsal pre-compiled the epochs)
    assert row["churn"]["compile_s"] == 0.0, row
    assert row["churn"]["cache_misses"] == 0, row
    assert row["qps"] > 0 and row["write_rows_per_s"] > 0, row
    assert row["p99_ms"] >= row["p50_ms"] > 0, row
    # toy-scale PQ: parity only loosely; the 0.01 bar is the 100k row's
    assert abs(row["recall_gap"]) < 0.25, row
    assert row["recall_mut"] > 0.3, row
    # the live recall canary rides this row (ISSUE 8): the estimate exists,
    # its interval is well-formed, and the zero-cold-compile assertion
    # above now ALSO covers the canary's sampling + shadow reranks (they
    # ran inside the attributed window, rehearsal-warmed per epoch)
    c = row["canary"]
    assert c is not None and c["rate"] == 0.05, row
    assert c["reranked"] > 0 and c["seen"] > 0, row
    assert c["wilson_low"] <= c["recall"] <= c["wilson_high"], row
    # toy-scale bracket: the 100k driver row asserts oracle_in_interval
    assert abs(c["recall"] - row["recall_mut"]) < 0.35, row


def test_serve_churn_flag_runs_only_the_churn_rows(monkeypatch):
    """`bench.py --serve-churn` is the stream parameter-iteration loop:
    setup + the two churn rows (IVF-PQ extend folds, CAGRA rebuild folds),
    nothing else."""
    import bench

    calls = []
    monkeypatch.setattr(bench, "_setup", lambda rows: calls.append("setup"))
    monkeypatch.setattr(
        bench, "_row_serve_churn",
        lambda rows: rows.append({"name": "serve_churn_ivf_pq_100k",
                                  "qps": 1.0}))
    monkeypatch.setattr(
        bench, "_row_serve_churn_cagra",
        lambda rows: rows.append({"name": "serve_churn_cagra_100k",
                                  "qps": 1.0}))
    monkeypatch.setattr(bench, "_run",
                        lambda rows: calls.append("run"))  # must NOT fire
    try:
        rc = bench.main(["--serve-churn"])
        assert rc == 0 and calls == ["setup"]
        names = {r.get("name") for r in bench._STATE["rows"]}
        assert {"serve_churn_ivf_pq_100k", "serve_churn_cagra_100k"} <= names
    finally:
        bench._STATE["rows"].clear()


def test_serve_churn_cagra_row_smoke():
    """The --serve-churn CAGRA row (ISSUE 6 acceptance measurement): same
    protocol as the IVF-PQ churn smoke, but every compaction is a REBUILD
    (no extend for graphs) — so the row proves the rehearsal covers the
    per-epoch rebuild program set too: >= 2 swaps, zero failed queries,
    zero cold compiles across the loaded window. Shrunk shapes; the
    absolute numbers are the TPU driver row's job."""
    import pytest

    pytest.importorskip("jax")
    import bench

    rows = []
    bench._row_serve_churn_cagra(rows, n=2500, d=32, k=5, itopk=16, threads=3,
                                 writer_steps=12, upserts_per_step=24,
                                 deletes_per_step=8, delta_capacity=128,
                                 compact_fill=0.75, max_batch=8,
                                 max_wait_us=500.0, ncl=32, n_eval=64)
    row = rows[-1]
    assert row["name"] == "serve_churn_cagra_100k" and "error" not in row, rows
    assert row["churn"]["failed"] == 0, row
    assert row["churn"]["compactions"] >= 2, row
    # zero cold compiles across the whole loaded window — every rebuild
    # fold, its publish warm + flip, and every flush (rehearsal-compiled)
    assert row["churn"]["compile_s"] == 0.0, row
    assert row["churn"]["cache_misses"] == 0, row
    assert row["qps"] > 0 and row["write_rows_per_s"] > 0, row
    # rebuild compactions actually rebuilt (tombstones reclaimed -> the
    # sealed row count tracks the live set, not a monotone append)
    assert all(w > 0 for w in row["churn"]["compaction_wall_s"]), row
    # exact sealed kind: rebuild-over-live-rows keeps recall at the fresh
    # -oracle point (CAGRA rebuild IS a fresh build over the live rows)
    assert abs(row["recall_gap"]) < 0.05, row


def test_build_ab_table_renders_from_artifact():
    """bench/build_ab.py --table: the BASELINE Round-6 follow-up table is
    generated FROM the artifact (no prose drift) — pure stdlib, no jax."""
    import importlib.util
    import pathlib

    spec = importlib.util.spec_from_file_location(
        "build_ab", pathlib.Path(__file__).resolve().parents[1]
        / "bench" / "build_ab.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    artifact = {
        "elapsed_s": 12.3,
        "config": {"n": [1000], "d": 8},
        "rows": [
            {"name": "em_ab_ivf_pq_100k",
             "full": {"warm_s": 10.0, "cold_s": 20.0, "recall": 0.98},
             "minibatch": {"warm_s": 6.0, "cold_s": 9.0, "recall": 0.979},
             "warm_cut": 0.4, "recall_gap": -0.001},
            {"name": "dist_overhead_100k",
             "full": {"single": {"warm_s": 10.0, "cold_s": 20.0},
                      "distributed": {"warm_s": 28.7, "cold_s": 40.0},
                      "warm_overhead": 1.87},
             "minibatch": {"single": {"warm_s": 6.0, "cold_s": 9.0},
                           "distributed": {"warm_s": 6.6, "cold_s": 10.0},
                           "warm_overhead": 0.1}},
            {"name": "cagra_build_ab_1000k", "shards": 8,
             "single": {"warm_s": 135.0, "cold_s": 300.0, "recall": 0.9714},
             "merged": {"warm_s": 50.0, "cold_s": 90.0, "recall": 0.9714},
             "warm_cut": 0.63, "recall_gap": 0.0},
            {"name": "em_ab_ivf_pq_1000k", "error": "RuntimeError: boom"},
        ],
    }
    table = mod.render_table(artifact)
    # every arm's numbers ride verbatim; the header names the generator
    for needle in ("em_ab_ivf_pq_100k", "warm_cut **0.4**", "0.9790",
                   "warm_overhead **0.1**", "cagra_build_ab_1000k",
                   "warm_cut **0.63**", "ERROR", "build_ab.py --table"):
        assert needle in table, (needle, table)
    # a markdown table: header + separator + one line per arm
    assert table.count("|") > 30


def test_canary_smoke_row():
    """The --canary-smoke bench row (ISSUE 8 acceptance measurement): QPS
    at sampling 0% vs 1% vs 5% with the background drainer reranking
    live, the Wilson interval bracketing the offline recall, and ZERO cold
    compiles across the whole monitored window (the canary's oracle was
    warmed at every rerank bucket). Shrunk shapes; absolute overhead
    numbers are the TPU driver row's job."""
    import pytest

    pytest.importorskip("jax")
    import bench

    rows = []
    bench._row_canary_smoke(rows, n=2500, d=32, n_lists=16, pq_dim=16, k=5,
                            n_probes=16, threads=3, per_thread=40,
                            rates=(0.0, 0.05, 0.25), max_batch=8,
                            max_wait_us=500.0, ncl=32, n_eval=64)
    row = rows[-1]
    assert row["name"] == "canary_smoke_100k" and "error" not in row, rows
    assert row["failed"] == 0, row
    assert set(row["qps_by_rate"]) == {"0", "0.05", "0.25"}, row
    assert all(v > 0 for v in row["qps_by_rate"].values()), row
    assert row["slowdown_at_5pct"] > 0, row
    # live monitoring must not compile anything, on or off the hot path
    assert row["compile_s"] == 0.0, row
    assert row["cache_misses"] == 0, row
    c = row["canary"]
    assert c["reranked"] > 0 and c["seen"] > 0, row
    assert c["wilson_low"] <= c["recall"] <= c["wilson_high"], row
    # the acceptance bracket: offline truth inside the live interval
    assert c["oracle_in_interval"], row
    assert abs(c["recall"] - row["recall_offline"]) < 0.2, row


def test_canary_smoke_flag_runs_only_the_canary_row(monkeypatch):
    """`bench.py --canary-smoke` is the quality-layer iteration loop: setup
    + the canary row, nothing else."""
    import bench

    calls = []
    monkeypatch.setattr(bench, "_setup", lambda rows: calls.append("setup"))
    monkeypatch.setattr(
        bench, "_row_canary_smoke",
        lambda rows: rows.append({"name": "canary_smoke_100k", "qps": 1.0}))
    monkeypatch.setattr(bench, "_run",
                        lambda rows: calls.append("run"))  # must NOT fire
    try:
        rc = bench.main(["--canary-smoke"])
        assert rc == 0 and calls == ["setup"]
        assert any(r.get("name") == "canary_smoke_100k"
                   for r in bench._STATE["rows"])
    finally:
        bench._STATE["rows"].clear()


def test_drift_sweep_small_scale():
    """bench/drift_sweep.py at CI scale: the heavytail twin fires the
    detector, the isotropic one stays silent, on both the query-sample and
    compaction-stat feeds (the ISSUE 8 satellite sweep; full scales run on
    the driver)."""
    import importlib.util
    import pathlib

    import pytest

    pytest.importorskip("jax")
    spec = importlib.util.spec_from_file_location(
        "drift_sweep", pathlib.Path(__file__).resolve().parents[1]
        / "bench" / "drift_sweep.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    artifact = mod.run_sweep(mod.SMALL_SCALES)
    assert len(artifact["rows"]) == 2
    by = {r["name"].split("_")[1]: r for r in artifact["rows"]}
    assert by["heavytail"]["ok"] and by["heavytail"]["compaction"]["drifted"]
    assert by["isotropic"]["ok"] and not by["isotropic"]["queries"]["drifted"]
    # drift state is per feed: the query-sample AND compaction-stat feeds
    # each advise once on their own transition
    assert by["heavytail"]["retune_events"] == 2
    assert by["isotropic"]["retune_events"] == 0
    table = mod.render_table(artifact)
    assert "drift_heavytail_2k_d32" in table and "**ok**" in table


def test_tune_smoke_row():
    """The --tune-smoke bench row (ISSUE 7): a tiny-budget sweep must
    produce a full row — chosen vs grid-head operating point with the QPS
    ratio — and the engine's choice rule makes chosen match-or-beat the
    head at equal-or-better recall by construction."""
    import pytest

    pytest.importorskip("jax")
    import bench

    rows = []
    bench._row_tune_smoke(rows, n=2000, d=16, ncl=32, n_lists=16, k=5,
                          m=64, repeats=1)
    row = rows[-1]
    assert row["name"] == "tune_smoke_10k" and "error" not in row, rows
    assert row["n_trials"] == 3, row
    assert row["decision"].startswith("ivf_pq/float32/"), row
    assert row["qps"] >= row["default_qps"], row
    assert row["recall"] >= row["recall_target"], row
    assert row["chosen_qps_over_default"] >= 1.0, row


def test_tune_smoke_flag_runs_only_the_tune_row(monkeypatch):
    """`bench.py --tune-smoke` is the autotune iteration loop: setup + the
    tune row, nothing else."""
    import bench

    calls = []
    monkeypatch.setattr(bench, "_setup", lambda rows: calls.append("setup"))
    monkeypatch.setattr(
        bench, "_row_tune_smoke",
        lambda rows: rows.append({"name": "tune_smoke_10k", "qps": 1.0}))
    monkeypatch.setattr(bench, "_run",
                        lambda rows: calls.append("run"))  # must NOT fire
    try:
        rc = bench.main(["--tune-smoke"])
        assert rc == 0 and calls == ["setup"]
        assert any(r.get("name") == "tune_smoke_10k"
                   for r in bench._STATE["rows"])
    finally:
        bench._STATE["rows"].clear()


def test_serve_shard_row_smoke():
    """The --serve-shard bench row (ISSUE 9 acceptance measurement) must
    produce a full row: a QPS ladder over shard counts, >= 2 STAGGERED
    one-shard-per-cycle compactions with zero failed queries, the
    rehearsal-backed zero-cold-compile proof (canary reranks included),
    and the fresh-oracle recall inside the live canary's interval. Shrunk
    shapes — absolute QPS scaling is the driver row's job (the row carries
    `cores` so the artifact prices the CPU-mesh ceiling in)."""
    import pytest

    pytest.importorskip("jax")
    import bench

    rows = []
    bench._row_serve_shard(rows, n=2400, d=32, n_lists=32, k=5, n_probes=16,
                           shard_counts=(1, 2), threads=3, per_thread=20,
                           writer_steps=12, upserts_per_step=24,
                           deletes_per_step=8, delta_capacity=128,
                           compact_fill=0.5, max_batch=8, max_wait_us=500.0,
                           ncl=32, n_eval=64)
    row = rows[-1]
    assert row["name"] == "serve_shard_churn_100k" and "error" not in row, rows
    assert row["churn"]["failed"] == 0, row
    assert row["churn"]["compactions"] >= 2, row
    # staggered: every fold names its shard; with hash-balanced writes the
    # folds walk more than one shard across the window
    shards_folded = row["churn"]["compaction_shards"]
    assert len(shards_folded) == row["churn"]["compactions"], row
    assert len(set(shards_folded)) >= 2, row
    # zero cold compiles across the whole loaded churn window — flushes,
    # staggered folds, publish warms, canary reranks (rehearsal-compiled)
    assert row["churn"]["compile_s"] == 0.0, row
    assert row["churn"]["cache_misses"] == 0, row
    assert set(row["qps_by_shards"]) == {"1", "2"}, row
    assert all(v > 0 for v in row["qps_by_shards"].values()), row
    assert row["cores"] >= 1 and row["shards"] == 2, row
    assert row["qps"] > 0 and row["write_rows_per_s"] > 0, row
    assert row["p99_ms"] >= row["p50_ms"] > 0, row
    # proportional sizing holds recall near the single-device oracle even
    # at toy scale (exhaustive-ish probes)
    assert abs(row["recall_gap"]) < 0.25, row
    c = row["canary"]
    assert c["reranked"] > 0 and c["seen"] > 0, row
    assert c["wilson_low"] <= c["recall"] <= c["wilson_high"], row
    # toy-scale bracket (a ~10-rerank reservoir): the 100k driver row
    # asserts the strict canary.oracle_in_interval acceptance bit
    assert abs(c["recall"] - row["recall_mut"]) < 0.35, row


def test_serve_shard_flag_runs_only_the_shard_row(monkeypatch):
    """`bench.py --serve-shard` is the sharded-tier iteration loop: setup
    + the shard row, nothing else."""
    import bench

    calls = []
    monkeypatch.setattr(bench, "_setup", lambda rows: calls.append("setup"))
    monkeypatch.setattr(
        bench, "_row_serve_shard",
        lambda rows: rows.append({"name": "serve_shard_churn_100k",
                                  "qps": 1.0}))
    monkeypatch.setattr(bench, "_run",
                        lambda rows: calls.append("run"))  # must NOT fire
    try:
        rc = bench.main(["--serve-shard"])
        assert rc == 0 and calls == ["setup"]
        assert any(r.get("name") == "serve_shard_churn_100k"
                   for r in bench._STATE["rows"])
    finally:
        bench._STATE["rows"].clear()


def test_mem_smoke_row():
    """The --mem-smoke bench row (ISSUE 10): publish→retire cycles with
    flat steady-state peaks, levels returning to baseline + one live
    index, zero steady-state compiles, a clean retirement audit, and the
    plan-vs-measured bracket — every assertion lives IN the row body, so
    a violation converts to an error row; here the small-scale twin must
    come back clean."""
    import pytest

    pytest.importorskip("jax")
    import bench

    rows = []
    bench._row_mem_smoke(rows, n=20_000, d=32, n_lists=128, cycles=3)
    row = rows[-1]
    assert row["name"] == "mem_smoke_100k" and "error" not in row, rows
    assert row["cycles"] == 3
    assert row["audit_clean"] is True
    assert row["steady_compile_s"] == 0.0
    assert 0.8 <= row["plan_ratio"] <= 1.2, row
    assert len(row["peak_bytes_by_cycle"]) == 3
    # levels flat: every cycle ends at baseline + exactly one live index
    lv = row["level_bytes_by_cycle"]
    assert max(lv) - min(lv) == 0, row


def test_mem_smoke_flag_runs_only_the_mem_row(monkeypatch):
    """`bench.py --mem-smoke` is the memory-ledger iteration loop: setup
    + the mem row, nothing else."""
    import bench

    calls = []
    monkeypatch.setattr(bench, "_setup", lambda rows: calls.append("setup"))
    monkeypatch.setattr(
        bench, "_row_mem_smoke",
        lambda rows: rows.append({"name": "mem_smoke_100k",
                                  "audit_clean": True}))
    monkeypatch.setattr(bench, "_run",
                        lambda rows: calls.append("run"))  # must NOT fire
    try:
        rc = bench.main(["--mem-smoke"])
        assert rc == 0 and calls == ["setup"]
        assert any(r.get("name") == "mem_smoke_100k"
                   for r in bench._STATE["rows"])
    finally:
        bench._STATE["rows"].clear()


def test_rows_carry_mem_field(monkeypatch):
    """Every guarded row scope attaches a "mem" field (peak device/host
    bytes via the ledger) when metrics are on, and none when disabled —
    the same contract as the "obs" attribution field."""
    import bench
    from raft_tpu import obs
    from raft_tpu.obs import mem as obs_mem

    rows = []

    def body():
        t = obs_mem.account("bench_probe", device_bytes=4096)
        obs_mem.release(t)
        rows.append({"name": "probe_row", "qps": 1.0})

    bench._row_guard(rows, "probe_row", body)
    row = next(r for r in rows if r["name"] == "probe_row")
    assert "mem" in row, row
    assert row["mem"]["device_peak_bytes"] >= (
        row["mem"]["device_bytes"])
    assert row["mem"]["device_peak_bytes"] - row["mem"]["device_bytes"] \
        >= 4096, "the scope peak must see the transient allocation"

    obs.disable()
    try:
        rows2 = []
        bench._row_guard(rows2, "probe_row2",
                         lambda: rows2.append({"name": "probe_row2"}))
        assert "mem" not in rows2[0], rows2
    finally:
        obs.enable()


def test_rows_carry_mem_tiers_watermark():
    """A row scope that held a TieredStore carries the per-tier WATERMARK
    under mem.tiers even though the store was a frame local freed before
    attribution attached (the live totals would read empty there); a
    scope without one carries no tiers field."""
    import numpy as np

    import bench
    from raft_tpu.stream.tiered import TieredStore

    rows = []

    def body():
        ts = TieredStore(np.zeros((64, 8), np.float32),
                         name="bench_probe_tier")
        assert ts.tier_bytes()["host"] == 64 * 8 * 4
        rows.append({"name": "tier_probe", "qps": 1.0})

    bench._row_guard(rows, "tier_probe", body)
    row = next(r for r in rows if r["name"] == "tier_probe")
    assert row["mem"]["tiers"]["host"] >= 64 * 8 * 4, row

    rows2 = []
    bench._row_guard(rows2, "plain_probe",
                     lambda: rows2.append({"name": "plain_probe"}))
    assert "tiers" not in rows2[0]["mem"], rows2


def test_fault_smoke_row():
    """The --fault-smoke availability row (ISSUE 11 acceptance): a
    replicated sharded mesh serves a loaded window during which one
    replica is killed and later revived. The row body asserts the
    acceptance bits itself (zero failed queries, breaker strikes
    observed, zero cold compiles after rehearsal); the small-scale twin
    must come back clean and carry the measured recovery."""
    import pytest

    pytest.importorskip("jax")
    import bench

    rows = []
    bench._row_fault_smoke(rows, n=4000, d=16, n_lists=32, k=5, n_probes=8,
                           steps=60, qbatch=16, fence_at=15, heal_at=40,
                           delta_capacity=256)
    row = rows[-1]
    assert row["name"] == "fault_smoke_100k" and "error" not in row, rows
    assert row["failed_queries"] == 0, row
    assert row["strikes"] > 0, row
    assert row["compile_s_loaded"] == 0.0, row
    assert row["recovery_s"] > 0, row
    assert row["qps"] > 0 and row["replicas"] == 2, row
    # the event plane saw the fence and the heal (ISSUE 17): the row
    # carries per-kind counts, gated by compare.py on presence
    assert row["events"]["replica_fenced"] >= 1, row
    assert row["events"]["replica_unfenced"] >= 1, row


def test_crash_recovery_row():
    """The --fault-smoke crash-durability row (ISSUE 11 acceptance): an
    injected SimulatedCrash between WAL append and memtable insert, then
    load() + WAL replay + warm(). The row body asserts id-for-id parity
    with an uncrashed twin and a compile-free post-warm window; here the
    small-scale twin must land with recall_recovered == 1.0 (the field
    bench/compare.py gates like every recall field) and the measured
    replay economics."""
    import pytest

    pytest.importorskip("jax")
    import bench

    rows = []
    bench._row_crash_recovery(rows, n=4000, d=16, n_lists=32, k=5,
                              n_probes=8, write_steps=10, write_rows=16,
                              delete_rows=4, delta_capacity=512, n_eval=64)
    row = rows[-1]
    assert row["name"] == "crash_recovery_100k" and "error" not in row, rows
    assert row["recall_recovered"] == 1.0, row
    assert row["wal_records"] == 2 * 9 + 1, row  # 9 upsert+delete pairs + 1
    assert row["wal_bytes"] > 0, row
    assert row["recovery_s"] > 0 and row["replay_rows_per_s"] > 0, row
    assert row["compile_s_post_warm"] == 0.0, row


def test_reshard_churn_row():
    """The --reshard elasticity row (ISSUE 13 acceptance): a loaded
    replicated mesh doubles its shard count online with one replica
    killed mid-migration. The row body asserts the acceptance bits itself
    (zero failed queries, strikes observed, zero cold compiles after
    rehearsal, recall held across the flip); the small-scale twin must
    come back clean with the measured crash-mid-reshard recovery."""
    import pytest

    pytest.importorskip("jax")
    import bench

    rows = []
    bench._row_reshard_churn(rows, n=4000, d=16, n_lists=32, k=5,
                             n_probes=8, steps=16, qbatch=16, reshard_at=8,
                             write_every=4, write_rows=8,
                             delta_capacity=512, n_eval=32, readers=2)
    row = rows[-1]
    assert row["name"] == "reshard_churn_100k" and "error" not in row, rows
    assert row["failed_queries"] == 0, row
    assert row["shards_from"] == 2 and row["shards_to"] == 4, row
    assert row["strikes"] > 0, row
    assert row["compile_s_loaded"] == 0.0, row
    assert row["rows_moved"] >= 4000, row
    assert row["carried_over"] >= 1, row  # the mid-migration write moved
    assert row["recall_post"] >= row["recall_pre"] - 0.02, row
    assert row["recall_crash_recovered"] == 1.0, row
    assert row["crash_recovery_s"] > 0, row
    assert row["wal_records_replayed"] > 0, row
    assert row["qps"] > 0 and row["replicas"] == 2, row
    # the event plane saw the migration and the mid-flight kill (ISSUE 17)
    assert row["events"]["reshard_started"] >= 1, row
    assert row["events"]["reshard_flip"] >= 1, row
    assert row["events"]["replica_fenced"] >= 1, row


def test_reshard_flag_runs_only_the_reshard_row(monkeypatch):
    """`bench.py --reshard` is the elasticity iteration loop: setup + the
    reshard row, nothing else."""
    import bench

    calls = []
    monkeypatch.setattr(bench, "_setup", lambda rows: calls.append("setup"))
    monkeypatch.setattr(
        bench, "_row_reshard_churn",
        lambda rows: rows.append({"name": "reshard_churn_100k",
                                  "failed_queries": 0}))
    monkeypatch.setattr(bench, "_run",
                        lambda rows: calls.append("run"))  # must NOT fire
    try:
        rc = bench.main(["--reshard"])
        assert rc == 0 and calls == ["setup"]
        names = {r.get("name") for r in bench._STATE["rows"]}
        assert "reshard_churn_100k" in names
    finally:
        bench._STATE["rows"].clear()


def test_fault_smoke_flag_runs_only_the_fault_rows(monkeypatch):
    """`bench.py --fault-smoke` is the availability iteration loop: setup
    + the two fault rows, nothing else."""
    import bench

    calls = []
    monkeypatch.setattr(bench, "_setup", lambda rows: calls.append("setup"))
    monkeypatch.setattr(
        bench, "_row_fault_smoke",
        lambda rows: rows.append({"name": "fault_smoke_100k",
                                  "failed_queries": 0}))
    monkeypatch.setattr(
        bench, "_row_crash_recovery",
        lambda rows: rows.append({"name": "crash_recovery_100k",
                                  "recall_recovered": 1.0}))
    monkeypatch.setattr(bench, "_run",
                        lambda rows: calls.append("run"))  # must NOT fire
    try:
        rc = bench.main(["--fault-smoke"])
        assert rc == 0 and calls == ["setup"]
        names = {r.get("name") for r in bench._STATE["rows"]}
        assert {"fault_smoke_100k", "crash_recovery_100k"} <= names
    finally:
        bench._STATE["rows"].clear()


# ---------------------------------------------------------------------------
# bench/compare.py — the artifact regression gate (ISSUE 10 satellite)
# ---------------------------------------------------------------------------

def _artifact(rows):
    return {"parsed": {"metric": "m", "value": 1.0, "rows": rows}}


def test_compare_passes_on_identical_artifacts():
    sys.path.insert(0, str(REPO / "bench"))
    import compare

    art = _artifact([{"name": "a", "qps": 100.0, "recall": 0.9}])
    out = compare.compare(art, art)
    assert out["regressions"] == []
    assert out["rows"][0]["status"] == "ok"


def test_compare_flags_qps_and_recall_regressions():
    sys.path.insert(0, str(REPO / "bench"))
    import compare

    old = _artifact([
        {"name": "a", "qps": 100.0, "recall": 0.90},
        {"name": "b", "qps": 100.0, "recall_mut": 0.90},
        {"name": "c", "qps": 100.0},
        {"name": "gone", "qps": 5.0},
    ])
    new = _artifact([
        {"name": "a", "qps": 80.0, "recall": 0.90},     # -20% QPS
        {"name": "b", "qps": 99.0, "recall_mut": 0.85},  # -0.05 recall
        {"name": "c", "error": "boom"},                  # new error row
        {"name": "fresh", "qps": 1.0},
    ])
    out = compare.compare(old, new, qps_tol=0.15, recall_tol=0.01)
    assert sorted(out["regressions"]) == ["a", "b", "c"]
    assert out["only_old"] == ["gone"] and out["only_new"] == ["fresh"]
    # within tolerance → no gate
    ok = compare.compare(old, _artifact([
        {"name": "a", "qps": 90.0, "recall": 0.895},
        {"name": "b", "qps": 100.0, "recall_mut": 0.90},
        {"name": "c", "qps": 100.0},
        {"name": "gone", "qps": 5.0},
    ]), qps_tol=0.15, recall_tol=0.01)
    assert ok["regressions"] == []


def test_compare_gates_on_lost_measurements():
    """Review regression: a QPS/recall field present in the old row but
    missing from the new is a gate failure, not a silent skip — a harness
    bug that drops the measurement must not pass as 'ok'."""
    sys.path.insert(0, str(REPO / "bench"))
    import compare

    old = _artifact([
        {"name": "a", "qps": 100.0, "recall": 0.90},
        {"name": "b", "qps": 100.0, "recall_mut": 0.90},
    ])
    new = _artifact([
        {"name": "a", "qps": 100.0},                    # recall vanished
        {"name": "b", "recall_mut": 0.90},              # qps vanished
    ])
    out = compare.compare(old, new)
    assert sorted(out["regressions"]) == ["a", "b"]
    missing = {(r["name"], c["field"]) for r in out["rows"]
               for c in r["checks"] if c.get("missing")}
    assert missing == {("a", "recall"), ("b", "qps")}
    # a field the NEW artifact gained gates nothing (new rows/fields
    # appear every round)
    ok = compare.compare(new, old)
    assert ok["regressions"] == []


def test_tiered_row():
    """The --tiered bench row (ISSUE 15 acceptance): the same corpus
    served all-HBM vs tiered under a device budget the raw rows exceed.
    Every acceptance bit lives IN the row body (bit-equal ids, flat
    per-tier bytes, zero failed queries, zero cold compiles) — the
    small-scale twin must come back clean with the host-hop cost and
    per-tier attribution recorded."""
    import pytest

    pytest.importorskip("jax")
    import bench

    rows = []
    bench._row_tiered(rows, n=20_000, d=32, n_lists=128, pq_dim=16, m=256,
                      bucket=128, waves=3, ncl=200)
    row = rows[-1]
    assert row["name"] == "tiered_100k" and "error" not in row, rows
    assert row["tier_residency"] == "host"
    assert row["store_bytes"] > row["budget_bytes"] - row["tier_bytes"][
        "device"], "the raw rows must exceed the device budget headroom"
    assert row["failed_queries"] == 0
    assert row["steady_compile_s"] == 0.0
    assert row["steady_cache_misses"] == 0
    assert row["recall"] == row["recall_hbm"]  # bit-equal twins
    assert row["tier_bytes"]["host"] == row["store_bytes"]
    assert row["h2d_bytes"] > 0 and row["host_hop_s"] >= 0.0
    assert row["qps"] > 0 and row["qps_hbm"] > 0
    # the row carries the journal's per-kind counts (ISSUE 17) — present
    # whenever metrics are on, gated by compare.py on presence
    assert isinstance(row.get("events"), dict), row


def test_ooc_build_row():
    """The --ooc-build bench row (ISSUE 19 acceptance): the same corpus
    built in-core vs streamed off a temp-file memmap. The hard claims —
    bit-equal indexes, streamed device peak inside plan(streamed)'s
    ±20% envelope — are asserted INSIDE the row body (a violation
    converts to an error row), so the small-scale twin coming back
    clean IS the acceptance check; the row just has to carry the
    attribution fields the compare.py gate and the round notes read."""
    import pytest

    pytest.importorskip("jax")
    import bench

    rows = []
    bench._row_ooc_build(rows, n=20_000, d=32, n_lists=128, pq_dim=8,
                         chunk_rows=4096, ncl=200)
    row = rows[-1]
    assert row["name"] == "ooc_build_100k" and "error" not in row, rows
    assert row["bit_equal"] is True
    assert row["recall"] == row["recall_incore"]  # bit-equal twins
    assert row["n_chunks"] == 5
    assert row["peak_dev_bytes"] > 0 and row["plan_dev_bytes"] > 0
    assert row["peak_host_bytes"] > 0 and row["plan_host_bytes"] > 0
    # the staging term is two chunks, independent of the corpus size
    assert row["staging_dev_bytes"] == 2 * 4096 * 32 * 4
    assert row["staging_dev_bytes"] < row["corpus_bytes"]
    assert row["build_s"] > 0 and row["build_s_incore"] > 0
    assert isinstance(row.get("events"), dict), row


def test_ooc_build_flag_runs_only_the_ooc_row(monkeypatch):
    """`bench.py --ooc-build` is the streamed-build iteration loop:
    setup + the ooc row, nothing else."""
    import bench

    calls = []
    monkeypatch.setattr(bench, "_setup", lambda rows: calls.append("setup"))
    monkeypatch.setattr(
        bench, "_row_ooc_build",
        lambda rows: rows.append({"name": "ooc_build_100k", "recall": 1.0}))
    monkeypatch.setattr(bench, "_run",
                        lambda rows: calls.append("run"))  # must NOT fire
    try:
        rc = bench.main(["--ooc-build"])
        assert rc == 0 and calls == ["setup"]
        assert any(r.get("name") == "ooc_build_100k"
                   for r in bench._STATE["rows"])
    finally:
        bench._STATE["rows"].clear()


def test_tiered_flag_runs_only_the_tiered_row(monkeypatch):
    """`bench.py --tiered` is the beyond-HBM iteration loop: setup + the
    tiered row, nothing else."""
    import bench

    calls = []
    monkeypatch.setattr(bench, "_setup", lambda rows: calls.append("setup"))
    monkeypatch.setattr(
        bench, "_row_tiered",
        lambda rows: rows.append({"name": "tiered_100k", "qps": 1.0}))
    monkeypatch.setattr(bench, "_run",
                        lambda rows: calls.append("run"))  # must NOT fire
    try:
        rc = bench.main(["--tiered"])
        assert rc == 0 and calls == ["setup"]
        assert any(r.get("name") == "tiered_100k"
                   for r in bench._STATE["rows"])
    finally:
        bench._STATE["rows"].clear()


def test_compare_gates_lost_tier_measurement():
    """The per-tier mem sub-fields gate like recall fields on PRESENCE: a
    tier measurement the old artifact had and the new lost must FAIL (a
    harness bug dropping the attribution cannot pass as 'ok'), while
    byte-level drift between runs gates nothing."""
    sys.path.insert(0, str(REPO / "bench"))
    import compare

    old = _artifact([
        {"name": "t", "qps": 100.0, "recall": 0.9,
         "mem": {"device_bytes": 1, "tiers": {"device": 10, "host": 99}}},
    ])
    drifted = _artifact([
        {"name": "t", "qps": 100.0, "recall": 0.9,
         "mem": {"device_bytes": 5, "tiers": {"device": 77, "host": 1}}},
    ])
    assert compare.compare(old, drifted)["regressions"] == [], (
        "byte drift must not gate — presence does")
    for lost in (
        {"mem": {"device_bytes": 1, "tiers": {"device": 10}}},  # host gone
        {"mem": {"device_bytes": 1}},                           # tiers gone
        {},                                                     # mem gone
    ):
        new = _artifact([{"name": "t", "qps": 100.0, "recall": 0.9, **lost}])
        out = compare.compare(old, new)
        assert out["regressions"] == ["t"], lost
        assert any(c.get("missing") and c["field"].startswith("mem.tiers.")
                   for r in out["rows"] for c in r["checks"]), out
    # tiers the NEW artifact gained gate nothing
    assert compare.compare(_artifact([{"name": "t", "qps": 1.0}]),
                           old)["regressions"] == []


def test_compare_gates_lost_event_measurement():
    """The per-kind ``events`` sub-fields (ISSUE 17) gate like the
    per-tier mem sub-fields on PRESENCE: an event kind the old artifact
    observed and the new lost must FAIL (a fence window that stops
    producing replica_fenced events is a lost measurement), while count
    drift between runs gates nothing."""
    sys.path.insert(0, str(REPO / "bench"))
    import compare

    old = _artifact([
        {"name": "f", "qps": 100.0,
         "events": {"replica_fenced": 1, "replica_unfenced": 1}},
    ])
    drifted = _artifact([
        {"name": "f", "qps": 100.0,
         "events": {"replica_fenced": 7, "replica_unfenced": 3}},
    ])
    assert compare.compare(old, drifted)["regressions"] == [], (
        "count drift must not gate — presence does")
    for lost in (
        {"events": {"replica_fenced": 1}},   # unfenced kind gone
        {},                                  # events field gone
    ):
        new = _artifact([{"name": "f", "qps": 100.0, **lost}])
        out = compare.compare(old, new)
        assert out["regressions"] == ["f"], lost
        assert any(c.get("missing") and c["field"].startswith("events.")
                   for r in out["rows"] for c in r["checks"]), out
    # kinds the NEW artifact gained gate nothing
    assert compare.compare(_artifact([{"name": "f", "qps": 1.0}]),
                           old)["regressions"] == []


def test_compare_table_and_exit_codes(tmp_path, capsys):
    sys.path.insert(0, str(REPO / "bench"))
    import compare

    old = _artifact([{"name": "a", "qps": 100.0, "recall": 0.9}])
    bad = _artifact([{"name": "a", "qps": 10.0, "recall": 0.9}])
    po, pb = tmp_path / "old.json", tmp_path / "bad.json"
    po.write_text(json.dumps(old))
    pb.write_text(json.dumps(bad))
    assert compare.main([str(po), str(po), "--table"]) == 0
    out = capsys.readouterr().out
    assert "PASS" in out and "REGRESSION" not in out
    assert compare.main([str(po), str(pb), "--table"]) == 1
    out = capsys.readouterr().out
    assert "**REGRESSION**" in out and "FAIL: a" in out


def test_compare_bench_r05_vs_itself_passes():
    """The committed BENCH_r05 artifact compared against itself passes
    the gate (the ISSUE 10 acceptance bar for the tool's IO path: real
    driver wrapper, real row vocabulary, rc 0)."""
    sys.path.insert(0, str(REPO / "bench"))
    import compare

    art = json.loads((REPO / "BENCH_r05.json").read_text())
    out = compare.compare(art, art)
    assert out["regressions"] == []
    assert len(out["rows"]) >= 5  # the artifact's named rows all matched
    assert compare.main([str(REPO / "BENCH_r05.json"),
                         str(REPO / "BENCH_r05.json")]) == 0


def test_quant_funnel_row():
    """The --quant bench row (ISSUE 16 acceptance): the same corpus built
    classic vs 1bit-funnel with identical codec seeds, swept over
    tune.funnel_grid. Every acceptance bit lives IN the row body (width-1
    bit-equality, recall anchor, >=2x rows-per-HBM-byte, zero cold
    compiles) — the small-scale twin must come back clean with the
    frontier recorded in the decision evidence."""
    import pytest

    pytest.importorskip("jax")
    import bench

    rows = []
    bench._row_quant_funnel(rows, n=20_000, d=64, n_lists=128, pq_dim=32,
                            m=256, bucket=128, waves=3, ncl=200, repeats=1)
    row = rows[-1]
    assert row["name"] == "quant_funnel_100k" and "error" not in row, rows
    assert row["capacity_x"] >= 2.0
    assert row["bytes_per_row"] < row["bytes_per_row_classic"]
    assert row["rows_per_hbm_byte"] > row["rows_per_hbm_byte_classic"]
    assert row["recall"] >= row["recall_classic"] - 0.02
    assert row["steady_compile_s"] == 0.0
    assert row["steady_cache_misses"] == 0
    assert row["qps"] > 0 and row["qps_classic"] > 0
    assert row["n_trials"] >= 5 and row["frontier"], row
    assert row["chosen"]["funnel_widen"] >= 1


def test_quant_flag_runs_only_the_quant_row(monkeypatch):
    """`bench.py --quant` is the funnel iteration loop: setup + the quant
    row, nothing else."""
    import bench

    calls = []
    monkeypatch.setattr(bench, "_setup", lambda rows: calls.append("setup"))
    monkeypatch.setattr(
        bench, "_row_quant_funnel",
        lambda rows: rows.append({"name": "quant_funnel_100k", "qps": 1.0}))
    monkeypatch.setattr(bench, "_run",
                        lambda rows: calls.append("run"))  # must NOT fire
    try:
        rc = bench.main(["--quant"])
        assert rc == 0 and calls == ["setup"]
        assert any(r.get("name") == "quant_funnel_100k"
                   for r in bench._STATE["rows"])
    finally:
        bench._STATE["rows"].clear()


def test_compare_gates_lost_capacity_measurement():
    """The funnel capacity fields (bytes_per_row / rows_per_hbm_byte)
    gate like recall fields on PRESENCE: a capacity measurement the old
    artifact had and the new lost must FAIL (a harness bug dropping the
    claim cannot pass as 'ok'), while byte-price drift between runs
    gates nothing."""
    sys.path.insert(0, str(REPO / "bench"))
    import compare

    old = _artifact([
        {"name": "q", "qps": 100.0, "recall": 0.9,
         "bytes_per_row": 20, "rows_per_hbm_byte": 0.05},
    ])
    drifted = _artifact([
        {"name": "q", "qps": 100.0, "recall": 0.9,
         "bytes_per_row": 36, "rows_per_hbm_byte": 0.027},
    ])
    assert compare.compare(old, drifted)["regressions"] == [], (
        "byte-price drift must not gate — presence does")
    for lost in (
        {"bytes_per_row": 20},   # rows_per_hbm_byte gone
        {"rows_per_hbm_byte": 0.05},  # bytes_per_row gone
        {},                      # both gone
    ):
        new = _artifact([{"name": "q", "qps": 100.0, "recall": 0.9, **lost}])
        out = compare.compare(old, new)
        assert out["regressions"] == ["q"], lost
        missing = [c["field"] for r in out["rows"] for c in r["checks"]
                   if c.get("missing")]
        assert set(missing) <= {"bytes_per_row", "rows_per_hbm_byte"}, out
        assert missing, out
    # capacity fields the NEW artifact gained gate nothing
    assert compare.compare(_artifact([{"name": "q", "qps": 1.0}]),
                           old)["regressions"] == []


# ---------------------------------------------------------------------------
# bench.py --controller — the closed-loop controller rows (ISSUE 18)
# ---------------------------------------------------------------------------

def test_controller_drift_row():
    """The --controller drift row (ISSUE 18 acceptance): a heavytail
    corpus served at a collapsed operating point recovers through the
    sensor → sweep → warm-republish loop. Every acceptance bit lives IN
    the row body (zero failed queries, zero cold compiles after
    rehearsal, recall recovered, the causal seq chain off the journal) —
    the small-scale twin must come back clean."""
    import pytest

    pytest.importorskip("jax")
    import bench

    rows = []
    bench._row_controller_drift(rows, n=6000, d=32, ncl=64, n_lists=64,
                                k=5, m=128, n_eval=64, qbatch=32)
    row = rows[-1]
    assert row["name"] == "controller_drift_100k" and "error" not in row, \
        rows
    assert row["failed_queries"] == 0, row
    assert row["recall_recovered"] > row["pre_retune_at_k"], row
    assert row["retuned_version"] == 2, row
    assert row["compile_s_loaded"] == 0.0, row
    assert row["trigger_seq"] < row["decision_seq"], row
    # the event plane saw the whole loop (gated by compare.py on presence)
    assert row["events"]["retune_advised"] >= 1, row
    assert row["events"]["control/decision"] >= 1, row
    assert row["events"]["control/action_completed"] >= 1, row


def test_controller_ramp_row():
    """The --controller ramp row (ISSUE 18 acceptance): an upsert ramp
    trips the compactor's reshard watermark and the controller doubles
    the topology online. The row body asserts the acceptance bits itself
    (zero failed queries, zero cold compiles, recall held, sensor →
    decision → reshard_started → completed seq chain); the small-scale
    twin must come back clean."""
    import pytest

    pytest.importorskip("jax")
    import bench

    rows = []
    bench._row_controller_ramp(rows, n=4000, d=16, n_lists=32, k=5,
                               n_probes=8, qbatch=16, n_eval=32,
                               ramp_steps=4, ramp_rows=64,
                               delta_capacity=512)
    row = rows[-1]
    assert row["name"] == "controller_ramp_100k" and "error" not in row, \
        rows
    assert row["failed_queries"] == 0, row
    assert row["shards_from"] == 2 and row["shards_to"] == 4, row
    assert row["compile_s_loaded"] == 0.0, row
    assert row["recall_post"] >= row["recall_pre"] - 0.02, row
    assert row["trigger_seq"] < row["decision_seq"], row
    assert row["events"]["reshard_advised"] >= 1, row
    assert row["events"]["control/decision"] >= 1, row
    assert row["events"]["reshard_committed"] >= 1, row
    assert row["events"]["control/action_completed"] >= 1, row


def test_controller_flag_runs_only_the_controller_rows(monkeypatch):
    """`bench.py --controller` is the control-plane iteration loop: setup
    + the two controller rows, nothing else."""
    import bench

    calls = []
    monkeypatch.setattr(bench, "_setup", lambda rows: calls.append("setup"))
    monkeypatch.setattr(
        bench, "_row_controller_drift",
        lambda rows: rows.append({"name": "controller_drift_100k",
                                  "failed_queries": 0}))
    monkeypatch.setattr(
        bench, "_row_controller_ramp",
        lambda rows: rows.append({"name": "controller_ramp_100k",
                                  "failed_queries": 0}))
    monkeypatch.setattr(bench, "_run",
                        lambda rows: calls.append("run"))  # must NOT fire
    try:
        rc = bench.main(["--controller"])
        assert rc == 0 and calls == ["setup"]
        names = {r.get("name") for r in bench._STATE["rows"]}
        assert {"controller_drift_100k", "controller_ramp_100k"} <= names
    finally:
        bench._STATE["rows"].clear()


# ---------------------------------------------------------------------------
# bench.py --net-serve — the network front-door rows (ISSUE 20)
# ---------------------------------------------------------------------------

def test_net_serve_row():
    """The --net-serve A/B row (ISSUE 20 acceptance): the same published
    service driven in-process and over the loopback wire — recall must be
    IDENTICAL across the two paths (same index, same flush programs), the
    QPS ladder and the wire/queue/flush p99 decomposition ride the row,
    and the serving window is compile-free. The shrunk-scale twin must
    come back clean; the row body asserts zero failures and zero cold
    compiles itself."""
    import pytest

    pytest.importorskip("jax")
    import bench

    rows = []
    bench._row_net_serve(rows, n=4000, d=16, n_lists=32, n_probes=8, k=5,
                         thread_ladder=(1, 2), per_thread=25, max_batch=16,
                         n_eval=64, ncl=32)
    row = rows[-1]
    assert row["name"] == "net_serve_100k" and "error" not in row, rows
    assert row["recall_wire"] == row["recall_inproc"], row
    assert row["cache_misses"] == 0, row
    assert row["qps"] > 0 and row["qps_inproc"] > 0, row
    assert set(row["qps_by_threads"]) == {"inproc", "wire"}, row
    assert {"wire_total_ms", "queue_ms", "flush_ms"} == \
        set(row["p99_decomp"]), row


def test_net_kill_worker_row():
    """The --net-serve kill row (ISSUE 20 acceptance): a worker process
    SIGKILLed under closed-loop wire load becomes strike→fence→failover
    with ZERO failed queries and exact post-kill recall; the surviving
    fleet reports zero cold compiles. The shrunk 2x2 mesh must come back
    clean (the row body asserts the acceptance bits itself)."""
    import pytest

    pytest.importorskip("jax")
    import bench

    rows = []
    bench._row_net_kill_worker(rows, n=2000, d=16, k=5, threads=3,
                               duration_s=2.5, kill_after_s=1.0,
                               n_eval=32, max_batch=16)
    row = rows[-1]
    assert row["name"] == "net_kill_worker_100k" and "error" not in row, rows
    assert row["failed"] == 0, row
    assert row["failovers"] >= 1, row
    assert row["recall_after_kill"] == 1.0, row
    assert row["fleet"]["cache_misses"] == 0, row
    assert row["healthy_by_shard"] == [1, 2], row


def test_net_serve_flag_runs_only_the_net_rows(monkeypatch):
    """`bench.py --net-serve` is the front-door iteration loop: setup +
    the two net rows, nothing else."""
    import bench

    calls = []
    monkeypatch.setattr(bench, "_setup", lambda rows: calls.append("setup"))
    monkeypatch.setattr(
        bench, "_row_net_serve",
        lambda rows: rows.append({"name": "net_serve_100k",
                                  "recall_wire": 1.0}))
    monkeypatch.setattr(
        bench, "_row_net_kill_worker",
        lambda rows: rows.append({"name": "net_kill_worker_100k",
                                  "failed": 0}))
    monkeypatch.setattr(bench, "_run",
                        lambda rows: calls.append("run"))  # must NOT fire
    try:
        rc = bench.main(["--net-serve"])
        assert rc == 0 and calls == ["setup"]
        names = {r.get("name") for r in bench._STATE["rows"]}
        assert {"net_serve_100k", "net_kill_worker_100k"} <= names
    finally:
        bench._STATE["rows"].clear()
