"""Network front door (ISSUE 20): shared httpd plumbing, wire schemas,
taxonomy→status error mapping with exact-type client reconstruction,
Retry-After hints through submit_with_retry, rid threading
wire→queue→flush, and the multi-process mesh (cross-process
scatter-gather, kill-a-worker strike→fence→failover, zero cold compiles).

Single-process tests run over loopback HTTP against real or stub
backends; the mesh test boots real worker processes (spawn), so it costs
seconds of startup — everything destructive happens inside one test
function so the kill ordering is deterministic.
"""

import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from raft_tpu import serve
from raft_tpu.core.errors import RaftError
from raft_tpu.neighbors import brute_force
from raft_tpu.net import wire
from raft_tpu.net._httpd import Httpd, Response, json_response
from raft_tpu.net.client import NetClient
from raft_tpu.net.mesh import MeshSpec, ProcessMesh
from raft_tpu.net.server import NetServer
from raft_tpu.obs import events as obs_events
from raft_tpu.obs import requestlog
from raft_tpu.serve import submit_with_retry
from raft_tpu.serve.errors import (DeadlineExceededError, MemoryBudgetError,
                                   OverloadedError, ReplicaUnavailableError,
                                   ServiceClosedError)
from raft_tpu.serve.service import SearchService

pytestmark = pytest.mark.net


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _get(url):
    try:
        with urllib.request.urlopen(url, timeout=5) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def _post_raw(url, payload, headers=None):
    """POST JSON, return (status, body_dict, headers) without raising."""
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(), method="POST",
        headers={"Content-Type": "application/json", **(headers or {})})
    try:
        with urllib.request.urlopen(req, timeout=5) as r:
            return r.status, json.loads(r.read().decode()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode()), dict(e.headers)


# ---------------------------------------------------------------------------
# shared httpd plumbing (satellite: one server pattern, not two)
# ---------------------------------------------------------------------------


class TestHttpd:
    def test_routing_get_post_and_404_contract(self):
        def echo(req):
            return json_response(200, {"method": req.method,
                                       "q": req.param("x"),
                                       "body": req.json() if req.body
                                       else None})

        with Httpd({("GET", "/a"): echo, ("POST", "/b"): echo}) as srv:
            base = f"http://127.0.0.1:{srv.port}"
            code, body = _get(base + "/a?x=1&x=2")
            assert code == 200 and json.loads(body)["q"] == "2"
            code, body, _ = _post_raw(base + "/b", {"k": 3})
            assert code == 200 and body["body"] == {"k": 3}
            # unknown path: loud 404 listing endpoints in registration order
            code, body = _get(base + "/nope")
            assert code == 404 and "endpoints: /a, /b" in body
            # registered path, wrong method: also the 404 contract
            code, body = _get(base + "/b")
            assert code == 404

    def test_handler_exception_is_500_not_hang(self):
        def boom(req):
            raise ValueError("kaput")

        with Httpd({("GET", "/x"): boom}) as srv:
            code, body = _get(f"http://127.0.0.1:{srv.port}/x")
            assert code == 500 and "kaput" in body

    def test_ephemeral_port_and_idempotent_stop(self):
        srv = Httpd({("GET", "/"): lambda r: Response(200, b"ok")})
        assert srv.port > 0
        srv.stop()
        srv.stop()  # idempotent

    def test_obs_exporter_rides_shared_httpd(self):
        from raft_tpu.obs.http import MetricsExporter

        with MetricsExporter(port=0) as exp:
            assert isinstance(exp._server, Httpd)
            code, _ = _get(f"http://127.0.0.1:{exp.port}/metrics")
            assert code == 200


# ---------------------------------------------------------------------------
# wire schemas
# ---------------------------------------------------------------------------


class TestWireSchemas:
    @pytest.mark.parametrize("dtype", ["float32", "int32", "int64", "uint8"])
    def test_array_roundtrip_bit_exact(self, rng, dtype):
        a = (rng.standard_normal((7, 5)) * 100).astype(dtype)
        b = wire.decode_array(wire.encode_array(a))
        assert b.dtype == a.dtype and np.array_equal(a, b)
        b[0, 0] += 1  # decoded arrays own their buffer (writable)

    def test_query_batch_roundtrip(self, rng):
        q = rng.standard_normal((3, 8)).astype(np.float32)
        name, q2, k = wire.decode_query_batch(
            wire.encode_query_batch("corpus", q, 10))
        assert name == "corpus" and k == 10 and np.array_equal(q, q2)

    def test_candidates_roundtrip(self, rng):
        d = rng.standard_normal((2, 4)).astype(np.float32)
        i = rng.integers(0, 100, (2, 4)).astype(np.int32)
        d2, i2 = wire.decode_candidates(wire.encode_candidates(d, i))
        assert np.array_equal(d, d2) and np.array_equal(i, i2)

    def test_malformed_envelopes_raise_rafterror(self):
        with pytest.raises(RaftError, match="malformed query batch"):
            wire.decode_query_batch({"v": 1, "k": 10})
        with pytest.raises(RaftError, match="malformed candidate set"):
            wire.decode_candidates({"rows": 1})
        with pytest.raises(RaftError, match="malformed control"):
            wire.decode_control({"v": 1})

    def test_control_roundtrip(self):
        op, payload = wire.decode_control(
            wire.encode_control("flush", name="corpus"))
        assert op == "flush" and payload == {"name": "corpus"}

    def test_spans_header_roundtrip(self):
        s = wire.encode_spans({"queue": 0.0012, "flush": 0.034,
                               "wire": 0.05})
        out = wire.decode_spans(s)
        assert out["queue"] == pytest.approx(0.0012, rel=1e-3)
        assert wire.decode_spans(None) == {}
        assert wire.decode_spans("junk=abc,ok=1.0") == {"ok": 1.0}


class TestErrorCodec:
    def test_status_ordering_subclass_before_base(self):
        # MemoryBudgetError IS an OverloadedError: 507 must win over 429
        assert wire.status_of(MemoryBudgetError("m")) == 507
        assert wire.status_of(OverloadedError("o")) == 429
        assert wire.status_of(DeadlineExceededError("d")) == 504
        assert wire.status_of(ReplicaUnavailableError("r")) == 503
        assert wire.status_of(ServiceClosedError("s")) == 503
        assert wire.status_of(RaftError("v")) == 400
        assert wire.status_of(ValueError("x")) == 500

    def test_structured_fields_roundtrip(self):
        exc = MemoryBudgetError("over", site="publish", budget_bytes=100,
                                accounted_bytes=90, need_bytes=20)
        code, body = wire.encode_error(exc)
        assert code == 507
        assert body["error"]["type"] == "MemoryBudgetError"
        back = wire.decode_error(body, status=code)
        assert type(back) is MemoryBudgetError
        assert (back.site, back.budget_bytes, back.accounted_bytes,
                back.need_bytes) == ("publish", 100, 90, 20)

    def test_retry_after_rides_fields(self):
        code, body = wire.encode_error(OverloadedError("full"),
                                       retry_after_s=0.125)
        back = wire.decode_error(body, status=code)
        assert type(back) is OverloadedError
        assert back.retry_after_s == 0.125

    def test_unknown_type_degrades_by_status(self):
        body = {"error": {"type": "FutureFancyError", "message": "x",
                          "fields": {}}}
        assert type(wire.decode_error(body, status=429)) is OverloadedError
        assert type(wire.decode_error(body, status=504)) is \
            DeadlineExceededError
        assert type(wire.decode_error(body, status=400)) is RaftError


# ---------------------------------------------------------------------------
# wire-level error mapping over a real front door (satellite: one case per
# taxonomy error — status code, structured body, exact-type re-raise)
# ---------------------------------------------------------------------------


class _RaisingService:
    """Front-door backend that refuses every submit with one exception."""

    def __init__(self, exc, hint=None):
        self.exc = exc
        self.hint = hint

    def submit(self, name, queries, k, timeout_s=None, rid=None):
        raise self.exc

    def queue_depth(self):
        return 3

    def retry_after_hint(self):
        assert self.hint is not None
        return self.hint


def _q(rng, n=1, d=4):
    return rng.standard_normal((n, d)).astype(np.float32)


class TestWireErrorMapping:
    @pytest.mark.parametrize("exc,code", [
        (OverloadedError("queue at 8/8 rows"), 429),
        (MemoryBudgetError("budget", site="upsert", budget_bytes=64,
                           accounted_bytes=60, need_bytes=10), 507),
        (DeadlineExceededError("late"), 504),
        (ReplicaUnavailableError("all dead", name="corpus/s0",
                                 replicas=2, fenced=2), 503),
        (ServiceClosedError("shut down"), 503),
        (RaftError("queries must be (rows, d)"), 400),
    ])
    def test_taxonomy_maps_and_reconstructs(self, rng, exc, code):
        hint = 0.05 if isinstance(exc, OverloadedError) else None
        with NetServer(_RaisingService(exc, hint=hint)) as srv:
            base = f"http://127.0.0.1:{srv.port}"
            payload = wire.encode_query_batch("corpus", _q(rng), 10)
            got_code, body, headers = _post_raw(base + "/v1/search", payload)
            # (a) the status code
            assert got_code == code
            # (b) the structured JSON error body
            assert body["error"]["type"] == type(exc).__name__
            assert str(exc) in body["error"]["message"]
            # (c) the client re-raises the EXACT type, fields intact
            cli = NetClient(base)
            with pytest.raises(type(exc)) as ei:
                cli.search("corpus", _q(rng), 10)
            assert type(ei.value) is type(exc)
            if isinstance(exc, MemoryBudgetError):
                assert body["error"]["fields"]["budget_bytes"] == 64
                assert (ei.value.site, ei.value.need_bytes) == ("upsert", 10)
            if isinstance(exc, ReplicaUnavailableError):
                assert (ei.value.replicas, ei.value.fenced) == (2, 2)
                assert ei.value.name == "corpus/s0"
            if isinstance(exc, OverloadedError):
                # the server's drain estimate rides header AND fields
                assert headers[wire.H_RETRY_AFTER] == "0.050"
                assert ei.value.retry_after_s == pytest.approx(0.05)

    def test_overload_from_real_service_full_queue(self, rng):
        ds = rng.standard_normal((32, 4)).astype(np.float32)
        svc = SearchService(max_batch=2, max_queue_rows=2,
                            start_workers=False)
        svc.publish("corpus", brute_force.BruteForce().build(ds), k=5,
                    warm=False)
        try:
            svc.submit("corpus", ds[:2], 5)  # fill the queue in-process
            with NetServer(svc) as srv:
                cli = NetClient(f"http://127.0.0.1:{srv.port}")
                with pytest.raises(OverloadedError) as ei:
                    cli.search("corpus", ds[:1], 5)
                # hint derived from live queue depth, never zero
                assert ei.value.retry_after_s > 0
        finally:
            svc.pump(force=True)
            svc.shutdown()

    def test_deadline_header_becomes_timeout(self, rng):
        ds = rng.standard_normal((32, 4)).astype(np.float32)
        svc = SearchService(max_batch=4, start_workers=False)
        svc.publish("corpus", brute_force.BruteForce().build(ds), k=5,
                    warm=False)
        try:
            with NetServer(svc) as srv:
                cli = NetClient(f"http://127.0.0.1:{srv.port}")
                with pytest.raises(DeadlineExceededError):
                    cli.search("corpus", ds[:1], 5, timeout_s=-1.0)
        finally:
            svc.shutdown()


# ---------------------------------------------------------------------------
# Retry-After hint through submit_with_retry (satellite)
# ---------------------------------------------------------------------------


class _ScriptedService:
    def __init__(self, script):
        self.script = list(script)
        self.calls = []

    def submit(self, name, queries, k, timeout_s=None):
        self.calls.append(timeout_s)
        if self.script:
            err = self.script.pop(0)
            if err is not None:
                raise err
        return "future"


def _overload_with_hint(hint):
    exc = OverloadedError("full")
    exc.retry_after_s = hint
    return exc


class TestRetryAfterHint:
    def test_hint_overrides_exponential_backoff(self):
        sleeps = []
        svc = _ScriptedService([_overload_with_hint(0.123), None])
        fut = submit_with_retry(svc, "main", None, 5, base_s=10.0,
                                jitter=0.0, sleep=sleeps.append)
        assert fut == "future"
        # jitter=0: the sleep IS the server's hint, not base_s
        assert sleeps == [pytest.approx(0.123)]

    def test_hint_jitters_upward_only(self):
        sleeps = []
        svc = _ScriptedService([_overload_with_hint(0.1)] * 4 + [None])
        rng = __import__("random").Random(3)
        submit_with_retry(svc, "main", None, 5, jitter=0.5, rng=rng,
                          max_attempts=10, sleep=sleeps.append)
        assert all(0.1 <= s <= 0.15 for s in sleeps)

    def test_refusal_without_hint_falls_back_to_backoff(self):
        sleeps = []
        svc = _ScriptedService([OverloadedError("full"), None])
        submit_with_retry(svc, "main", None, 5, base_s=0.01, jitter=0.0,
                          sleep=sleeps.append)
        assert sleeps == [pytest.approx(0.01)]

    def test_hint_still_respects_deadline(self):
        clock = FakeClock()
        svc = _ScriptedService([_overload_with_hint(5.0)] * 2)
        with pytest.raises(DeadlineExceededError):
            submit_with_retry(svc, "main", None, 5, timeout_s=1.0,
                              jitter=0.0, clock=clock,
                              sleep=lambda dt: clock.advance(dt))
        assert clock.t == 0.0  # refused to sleep into the budget
        assert len(svc.calls) == 1

    def test_deadline_exceeded_never_retries_regression(self):
        # even with a tempting hint attached, a spent deadline is final
        exc = DeadlineExceededError("late")
        exc.retry_after_s = 0.001
        svc = _ScriptedService([exc, None])
        with pytest.raises(DeadlineExceededError):
            submit_with_retry(svc, "main", None, 5, sleep=lambda dt: None)
        assert len(svc.calls) == 1


# ---------------------------------------------------------------------------
# rid threading: one trace spans wire→queue→flush
# ---------------------------------------------------------------------------


class TestRidThreading:
    def test_wire_rid_lands_in_request_log_with_spans(self, rng):
        ds = rng.standard_normal((64, 8)).astype(np.float32)
        rl = requestlog.RequestLog()
        svc = SearchService(max_batch=8, request_log=rl)
        svc.publish("corpus", brute_force.BruteForce().build(ds), k=5,
                    warm=False)
        try:
            with NetServer(svc, request_log=rl) as srv:
                cli = NetClient(f"http://127.0.0.1:{srv.port}")
                _, _, meta = cli.request("corpus", ds[:2], 5,
                                         rid="trace-abc-1")
                # the server echoes the client's rid
                assert meta["rid"] == "trace-abc-1"
                entry = rl.get("trace-abc-1")
                assert entry is not None
                assert "queue" in entry["spans_ms"]
                assert "flush" in entry["spans_ms"]
                # server-minted rids when the client sends none
                _, _, meta2 = cli.request("corpus", ds[:2], 5)
                assert meta2["rid"].startswith("wire-")
                assert rl.get(meta2["rid"]) is not None
        finally:
            svc.shutdown()

    def test_span_header_decomposes_wire_queue_flush(self, rng):
        ds = rng.standard_normal((64, 8)).astype(np.float32)
        rl = requestlog.RequestLog()
        svc = SearchService(max_batch=8, request_log=rl)
        svc.publish("corpus", brute_force.BruteForce().build(ds), k=5,
                    warm=False)
        try:
            with NetServer(svc, request_log=rl) as srv:
                cli = NetClient(f"http://127.0.0.1:{srv.port}")
                # the attach is best-effort per request; across a few
                # requests the decomposition must be served
                seen = set()
                for _ in range(5):
                    _, _, meta = cli.request("corpus", ds[:2], 5)
                    seen |= set(meta["spans"])
                assert {"wire", "queue", "flush"} <= seen
        finally:
            svc.shutdown()


# ---------------------------------------------------------------------------
# requestlog collect(resume=) cross-process constraint (bugfix satellite)
# ---------------------------------------------------------------------------


class TestCollectorCrossProcess:
    def test_same_process_resume_still_accumulates(self):
        with requestlog.collect() as col:
            requestlog.add_span("a", 0.1)
        with requestlog.collect(resume=col) as col2:
            requestlog.add_span("b", 0.2)
        assert col2 is col
        assert col.spans == {"a": 0.1, "b": 0.2}

    def test_cross_process_resume_degrades_to_fresh_collector(self):
        import os

        with requestlog.collect() as col:
            requestlog.add_span("a", 0.1)
        col.pid = os.getpid() + 1  # simulate a fork/spawn-carried collector
        with requestlog.collect(resume=col) as col2:
            requestlog.add_span("b", 0.2)
        # the foreign trace was NOT mutated; the degrade is marked
        assert col2 is not col
        assert col.spans == {"a": 0.1}
        assert col2.spans == {"b": 0.2}
        assert col2.notes["resume_degraded"] == "cross-process"


# ---------------------------------------------------------------------------
# the multi-process mesh
# ---------------------------------------------------------------------------


class TestProcessMesh:
    def test_scatter_gather_kill_failover_and_outage(self, rng):
        ds = rng.standard_normal((400, 8)).astype(np.float32)
        q = rng.standard_normal((6, 8)).astype(np.float32)
        # exact in-process answer to hold the mesh to
        svc = SearchService(max_batch=8)
        svc.publish("ref", brute_force.BruteForce().build(ds), k=10,
                    warm=False)
        _, ref_ids = svc.search("ref", q, 10)
        svc.shutdown()
        ref_sorted = np.sort(np.asarray(ref_ids), axis=1)

        seq0 = obs_events.last_seq()
        mesh = ProcessMesh(ds, spec=MeshSpec(n_shards=2, n_replicas=2,
                                             ks=(10,), max_batch=16))
        try:
            # cross-process scatter-gather == the single-index answer
            d, i = mesh.search("corpus", q, 10)
            assert np.array_equal(np.sort(np.asarray(i), axis=1), ref_sorted)
            assert np.all(np.diff(np.asarray(d), axis=1) >= 0)  # sorted

            # warm ladder rehearsed per worker: the fleet served with
            # ZERO cold compiles
            st = mesh.stats()
            assert st["workers"] == 4
            assert st["cache_misses"] == 0 and st["compile_s"] == 0.0

            # kill one worker: strike→fence→failover, NOT an outage.
            # Per-shard round-robin alternates the group's primary, so
            # within two searches the dead twin is tried (and struck)
            # deterministically.
            mesh.kill_worker(0, 0)
            for _ in range(2):
                d2, i2 = mesh.search("corpus", q, 10)
                assert np.array_equal(np.sort(np.asarray(i2), axis=1),
                                      ref_sorted)
            evs = obs_events.query(since_seq=seq0)
            kinds = [e["kind"] for e in evs]
            assert "net_worker_fenced" in kinds
            assert "net_worker_failover" in kinds
            health = mesh.health()
            assert health["shards"][0]["healthy"] == 1
            assert health["shards"][1]["healthy"] == 2

            # the front door folds mesh health: degraded, still 200
            with NetServer(mesh, stats=mesh.stats) as srv:
                cli = NetClient(f"http://127.0.0.1:{srv.port}")
                code, body = cli.healthz()
                assert code == 200 and body["status"] == "degraded"
                d3, i3 = cli.search("corpus", q, 10)
                assert np.array_equal(np.sort(np.asarray(i3), axis=1),
                                      ref_sorted)

                # kill the surviving twin: a whole group down IS an
                # outage — ReplicaUnavailableError, exact type + fields
                # across the wire
                mesh.kill_worker(0, 1)
                with pytest.raises(ReplicaUnavailableError) as ei:
                    cli.search("corpus", q, 10)
                assert type(ei.value) is ReplicaUnavailableError
                assert ei.value.replicas == 2
                assert ei.value.name.endswith("/s0")
                code, body = cli.healthz()
                assert code == 503 and body["status"] == "failing"
        finally:
            mesh.close()

    def test_writes_route_by_shared_hash_and_survive_a_dead_twin(self, rng):
        ds = rng.standard_normal((300, 8)).astype(np.float32)
        mesh = ProcessMesh(ds, spec=MeshSpec(n_shards=2, n_replicas=2,
                                             ks=(10,), max_batch=16))
        try:
            mesh.kill_worker(1, 0)  # a dead twin must not block writes
            rows = rng.standard_normal((8, 8)).astype(np.float32)
            ids = np.arange(50_000, 50_008)
            mesh.upsert("corpus", rows, ids=ids)
            _, got = mesh.search("corpus", rows, 10)
            assert np.array_equal(np.asarray(got)[:, 0], ids)
            assert mesh.delete("corpus", ids) == len(ids)
            _, got2 = mesh.search("corpus", rows, 10)
            assert not np.intersect1d(np.asarray(got2), ids).size
            with pytest.raises(RaftError):
                mesh.upsert("corpus", rows)  # global ids are required
        finally:
            mesh.close()
