"""Brute-force kNN + refine tests (reference analogue:
cpp/test/neighbors/tiled_knn.cu, knn.cu; refine via cpp/test/neighbors/refine.cu)."""

import numpy as np
import pytest
from scipy.spatial import distance as sp_dist

from raft_tpu.core import RaftError, Resources
from raft_tpu.neighbors import BruteForce, knn, knn_merge_parts, refine


def _exact(x, q, k, metric="sqeuclidean"):
    d = sp_dist.cdist(q, x, metric)
    idx = np.argsort(d, axis=1, kind="stable")[:, :k]
    return np.take_along_axis(d, idx, 1), idx


class TestKnn:
    def test_matches_exact(self, rng):
        x = rng.random((500, 16)).astype(np.float32)
        q = rng.random((40, 16)).astype(np.float32)
        dists, idx = knn(x, q, k=8)
        wd, wi = _exact(x, q, 8)
        np.testing.assert_allclose(np.asarray(dists), wd, atol=1e-3, rtol=1e-4)
        # indices may differ on ties; check distances of chosen ids instead
        chosen = sp_dist.cdist(q, x, "sqeuclidean")
        np.testing.assert_allclose(
            np.take_along_axis(chosen, np.asarray(idx), 1), wd, atol=1e-3, rtol=1e-4
        )

    def test_euclidean_metric(self, rng):
        x = rng.random((200, 8)).astype(np.float32)
        q = rng.random((10, 8)).astype(np.float32)
        dists, _ = knn(x, q, k=4, metric="euclidean")
        wd, _ = _exact(x, q, 4, "euclidean")
        np.testing.assert_allclose(np.asarray(dists), wd, atol=1e-3, rtol=1e-4)

    def test_inner_product_selects_max(self, rng):
        x = rng.random((100, 8)).astype(np.float32)
        q = rng.random((5, 8)).astype(np.float32)
        dists, idx = knn(x, q, k=3, metric="inner_product")
        full = q @ x.T
        want = np.sort(full, axis=1)[:, ::-1][:, :3]
        np.testing.assert_allclose(np.asarray(dists), want, rtol=1e-4)

    def test_tiny_workspace_tiling(self, rng):
        x = rng.random((300, 12)).astype(np.float32)
        q = rng.random((77, 12)).astype(np.float32)
        res = Resources(workspace_bytes=300 * 14 * 4 * 8)
        dists, idx = knn(x, q, k=5, res=res)
        wd, _ = _exact(x, q, 5)
        np.testing.assert_allclose(np.asarray(dists), wd, atol=1e-3, rtol=1e-4)

    def test_l1_metric_path(self, rng):
        x = rng.random((150, 6)).astype(np.float32)
        q = rng.random((9, 6)).astype(np.float32)
        dists, idx = knn(x, q, k=4, metric="l1")
        wd, _ = _exact(x, q, 4, "cityblock")
        np.testing.assert_allclose(np.asarray(dists), wd, atol=1e-3, rtol=1e-4)

    def test_index_class(self, rng):
        x = rng.random((80, 4)).astype(np.float32)
        q = rng.random((6, 4)).astype(np.float32)
        idx = BruteForce().build(x)
        d1, i1 = idx.search(q, 3)
        d2, i2 = knn(x, q, 3)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))

    def test_unbuilt_index_raises(self):
        with pytest.raises(RaftError, match="not built"):
            BruteForce().search(np.zeros((2, 3), np.float32), 1)

    def test_k_too_big(self, rng):
        with pytest.raises(RaftError):
            knn(np.zeros((5, 2), np.float32), np.zeros((1, 2), np.float32), 6)


class TestMergeParts:
    def test_merge_equals_global(self, rng):
        """Sharded kNN + merge must equal unsharded kNN — the multi-chip
        correctness property (ref: knn_merge_parts use at knn_brute_force.cuh:490)."""
        x = rng.random((400, 8)).astype(np.float32)
        q = rng.random((20, 8)).astype(np.float32)
        k = 6
        shards = np.split(np.arange(400), 4)
        pd, pi = [], []
        for s in shards:
            d, i = knn(x[s], q, k)
            pd.append(np.asarray(d))
            pi.append(np.asarray(i) + s[0])  # shard-local → global ids
        md, mi = knn_merge_parts(np.stack(pd), np.stack(pi))
        gd, gi = knn(x, q, k)
        np.testing.assert_allclose(np.asarray(md), np.asarray(gd), atol=1e-5)
        np.testing.assert_array_equal(np.sort(np.asarray(mi), 1), np.sort(np.asarray(gi), 1))


class TestRefine:
    def test_refine_improves_candidates(self, rng):
        x = rng.random((300, 10)).astype(np.float32)
        q = rng.random((15, 10)).astype(np.float32)
        # candidates: the true top-20 shuffled
        _, cand = _exact(x, q, 20)
        perm = rng.permutation(20)
        cand_shuffled = cand[:, perm]
        dists, ids = refine(x, q, cand_shuffled, k=5)
        wd, wi = _exact(x, q, 5)
        np.testing.assert_allclose(np.asarray(dists), wd, atol=1e-3, rtol=1e-4)
        np.testing.assert_array_equal(np.sort(np.asarray(ids), 1), np.sort(wi, 1))

    def test_refine_with_padding(self, rng):
        x = rng.random((50, 4)).astype(np.float32)
        q = rng.random((3, 4)).astype(np.float32)
        cand = np.array([[0, 1, -1, 2], [3, -1, -1, 4], [5, 6, 7, -1]], np.int32)
        dists, ids = refine(x, q, cand, k=3)
        ids = np.asarray(ids)
        # padding never outranks real candidates
        assert (ids[1, :2] >= 0).all()
        assert ids[1, 2] == -1
        assert np.isinf(np.asarray(dists)[1, 2])

    def test_refine_sqrt_metric(self, rng):
        x = rng.random((60, 5)).astype(np.float32)
        q = rng.random((4, 5)).astype(np.float32)
        _, cand = _exact(x, q, 10)
        dists, _ = refine(x, q, cand, k=4, metric="euclidean")
        wd, _ = _exact(x, q, 4, "euclidean")
        np.testing.assert_allclose(np.asarray(dists), wd, atol=1e-3, rtol=1e-4)


def test_knn_approx_mode(rng):
    """mode='approx' (TPU PartialReduce fast path) keeps high recall; on the
    CPU backend lax.approx_min_k reduces exactly for these sizes."""
    from raft_tpu.neighbors import knn

    x = rng.random((2000, 24)).astype(np.float32)
    q = rng.random((50, 24)).astype(np.float32)
    d_a, i_a = knn(x, q, 10, mode="approx")
    d_e, i_e = knn(x, q, 10, mode="exact")
    recall = np.mean([
        len(set(np.asarray(i_a)[i]) & set(np.asarray(i_e)[i])) / 10 for i in range(50)
    ])
    assert recall > 0.95
    import pytest as _pytest

    with _pytest.raises(Exception):
        knn(x, q, 10, mode="bogus")


def test_knn_bfloat16_compute(rng):
    """compute='bfloat16' (single-pass MXU contraction) preserves neighbor
    ordering on data with non-degenerate margins and rejects bad values."""
    from raft_tpu.neighbors import knn

    x = (10.0 * rng.random((1500, 32))).astype(np.float32)
    q = (10.0 * rng.random((40, 32))).astype(np.float32)
    d_b, i_b = knn(x, q, 10, compute="bfloat16")
    d_e, i_e = knn(x, q, 10, compute="float32")
    recall = np.mean([
        len(set(np.asarray(i_b)[i]) & set(np.asarray(i_e)[i])) / 10 for i in range(40)
    ])
    assert recall > 0.9
    # distances stay close in relative terms
    np.testing.assert_allclose(np.asarray(d_b), np.asarray(d_e), rtol=0.05, atol=0.5)
    import pytest as _pytest

    with _pytest.raises(Exception):
        knn(x, q, 10, compute="float16")


def test_pairwise_compute_knob(rng):
    from raft_tpu.distance import pairwise_distance

    x = rng.random((64, 16)).astype(np.float32)
    y = rng.random((48, 16)).astype(np.float32)
    d_b = np.asarray(pairwise_distance(x, y, metric="cosine", compute="bfloat16"))
    d_e = np.asarray(pairwise_distance(x, y, metric="cosine", compute="float32"))
    np.testing.assert_allclose(d_b, d_e, atol=2e-2)


class TestFilterUnderfill:
    """Shared filtered-underfill contract (ISSUE 5 satellite) — the
    documented -1/±inf sentinel, via the same checker every neighbors
    module now pins."""

    def test_underfill_sentinels(self, rng, check_filter_underfill):
        x = rng.random((400, 16)).astype(np.float32)
        q = rng.random((20, 16)).astype(np.float32)
        alive = [7, 123, 399]
        keep = np.zeros(400, bool)
        keep[alive] = True
        d, i = knn(x, q, k=6, sample_filter=keep)
        check_filter_underfill(d, i, alive, select_min=True)

    def test_underfill_sentinels_inner_product(self, rng,
                                               check_filter_underfill):
        x = rng.random((400, 16)).astype(np.float32)
        q = rng.random((20, 16)).astype(np.float32)
        alive = [0, 200]
        keep = np.zeros(400, bool)
        keep[alive] = True
        d, i = knn(x, q, k=5, metric="inner_product", sample_filter=keep)
        check_filter_underfill(d, i, alive, select_min=False)
