"""Warm-build story: serialize-after-build and the persistent jit cache.

VERDICT item: 1M builds are cold-jit dominated (IVF-Flat 120 s / CAGRA 320 s
cold vs seconds warm); repeat users need a path that skips both compile and
build. docs/warm_builds.md documents the workflow; these tests pin it.
"""

import time

import numpy as np
import pytest

import jax.numpy as jnp

from raft_tpu.neighbors import ivf_flat


@pytest.mark.slow
def test_load_is_much_faster_than_build(tmp_path, rng):
    x = jnp.asarray(rng.random((20_000, 32)).astype(np.float32))
    t0 = time.perf_counter()
    idx = ivf_flat.build(ivf_flat.IndexParams(n_lists=64, seed=0), x)
    import jax

    jax.block_until_ready(idx.list_data)
    build_s = time.perf_counter() - t0

    path = str(tmp_path / "warm.bin")
    ivf_flat.save(idx, path)
    t0 = time.perf_counter()
    idx2 = ivf_flat.load(path)
    jax.block_until_ready(idx2.list_data)
    load_s = time.perf_counter() - t0

    assert load_s * 5 < build_s, (load_s, build_s)
    d1, i1 = ivf_flat.search(ivf_flat.SearchParams(n_probes=8), idx, x[:16], 5)
    d2, i2 = ivf_flat.search(ivf_flat.SearchParams(n_probes=8), idx2, x[:16], 5)
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))


def test_warmup_entry_point(tmp_path):
    """raft_tpu.warmup must run the real build+search pipeline at the given
    shapes under the persistent cache and report timings (VERDICT r4 #6 —
    the AOT first-touch story; small shapes here, 1M measured in
    BASELINE.md's cold/warm table). The warmup itself runs in a subprocess:
    enable_compilation_cache permanently redirects this process's jax cache
    config, and the cache dir is a tmp_path deleted after the test."""
    import subprocess
    import sys
    from pathlib import Path

    repo = Path(__file__).resolve().parents[1]
    cache = tmp_path / "warmcache"
    code = f"""
import sys
sys.path.insert(0, {str(repo)!r})
from raft_tpu.core.platform import force_virtual_cpu
force_virtual_cpu(1)
import raft_tpu
from raft_tpu.neighbors import ivf_flat
out = raft_tpu.warmup("ivf_flat", n=2000, d=16, queries=64,
                      index_params=ivf_flat.IndexParams(n_lists=16, seed=0),
                      cache_dir={str(cache)!r})
assert out["build_s"] > 0 and out["search_s"] > 0, out
import os
assert os.path.isdir(out["cache_dir"]), out
print("WARMUP_OK")
"""
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, timeout=360)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "WARMUP_OK" in r.stdout

    # the kind guard needs no jax work and is safe in-process
    import raft_tpu
    from raft_tpu.core import RaftError

    with pytest.raises(RaftError, match="unknown index kind"):
        raft_tpu.warmup("flann", n=100, d=8)


def test_enable_compilation_cache_populates_dir(tmp_path):
    """The cache helper must configure jax to persist entries to disk. Run in
    a subprocess so this process's jax config/caches are untouched."""
    import subprocess
    import sys
    from pathlib import Path

    repo = Path(__file__).resolve().parents[1]
    cache = tmp_path / "jitcache"
    code = f"""
import sys
sys.path.insert(0, {str(repo)!r})
from raft_tpu.core.platform import force_virtual_cpu
force_virtual_cpu(1)
import raft_tpu.config
p = raft_tpu.config.enable_compilation_cache({str(cache)!r})
import jax, jax.numpy as jnp
jax.jit(lambda x: x * 2 + 1)(jnp.ones((128, 128))).block_until_ready()
import os
entries = [f for f in os.listdir(p) if not f.startswith('.')]
assert entries, 'no cache entries written'
print('CACHE_OK', len(entries))
"""
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, timeout=240)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "CACHE_OK" in r.stdout
    n_entries = int(r.stdout.split("CACHE_OK")[1].split()[0])

    # a second interpreter compiling the same program must REUSE the entries:
    # same count afterwards, not new ones (cross-process warm start, the
    # guarantee docs/warm_builds.md documents)
    r2 = subprocess.run([sys.executable, "-c", code], capture_output=True,
                        text=True, timeout=240)
    assert r2.returncode == 0, r2.stdout + r2.stderr
    n_entries2 = int(r2.stdout.split("CACHE_OK")[1].split()[0])
    assert n_entries2 == n_entries, (n_entries, n_entries2)


def test_warmup_warms_the_callers_k(tmp_path):
    """Regression (ADVICE r5 medium): the ivf_pq warmup used to search at
    ``max(k, 40)`` instead of the caller's k — the production k=10 program
    still compiled cold. The warmed search must be the SAME jitted program
    as the production search at those shapes: a production search after
    warmup adds ZERO new trace-cache entries to the k-carrying search jits.
    Runs in a subprocess because warmup permanently redirects the process's
    jax compilation-cache config."""
    import subprocess
    import sys
    from pathlib import Path

    repo = Path(__file__).resolve().parents[1]
    cache = tmp_path / "warmkcache"
    code = f"""
import sys
sys.path.insert(0, {str(repo)!r})
from raft_tpu.core.platform import force_virtual_cpu
force_virtual_cpu(1)
import jax, jax.numpy as jnp
import raft_tpu
from raft_tpu.neighbors import ivf_pq

ip = ivf_pq.IndexParams(n_lists=16, seed=0)
sp = ivf_pq.SearchParams(n_probes=4)
out = raft_tpu.warmup("ivf_pq", n=2000, d=16, k=7, queries=64,
                      index_params=ip, search_params=sp,
                      cache_dir={str(cache)!r})
assert out["search_s"] > 0, out

# production pipeline at the same shapes: identical data generation
# (warmup's own protocol, seed=0) so the built index has identical avals
kd, kq = jax.random.split(jax.random.key(0))
x = jax.random.uniform(kd, (2000, 16), jnp.float32)
q = jax.random.uniform(kq, (64, 16), jnp.float32)
idx = ivf_pq.build(ip, x)
before = (ivf_pq._pq_search._cache_size(),
          ivf_pq._pq_search_grouped._cache_size())
ivf_pq.search(sp, idx, q, 7)
after = (ivf_pq._pq_search._cache_size(),
         ivf_pq._pq_search_grouped._cache_size())
assert after == before, ("production k=7 search re-traced after a k=7 "
                         "warmup", before, after)
print("WARMK_OK")
"""
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, timeout=360)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "WARMK_OK" in r.stdout


def test_warmup_byte_dtype(tmp_path):
    """``warmup(..., dtype="uint8")`` must run the byte-dataset pipeline:
    random bytes in the target dtype so the s8 kernels and int8 list
    layouts compile exactly as production will run them."""
    import subprocess
    import sys
    from pathlib import Path

    repo = Path(__file__).resolve().parents[1]
    cache = tmp_path / "warmu8cache"
    code = f"""
import sys
sys.path.insert(0, {str(repo)!r})
from raft_tpu.core.platform import force_virtual_cpu
force_virtual_cpu(1)
import raft_tpu
from raft_tpu.neighbors import ivf_flat
out = raft_tpu.warmup("ivf_flat", n=2000, d=16, queries=64, dtype="uint8",
                      index_params=ivf_flat.IndexParams(n_lists=16, seed=0),
                      cache_dir={str(cache)!r})
assert out["build_s"] > 0 and out["search_s"] > 0, out
print("WARMU8_OK")
"""
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, timeout=360)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "WARMU8_OK" in r.stdout

    # the dtype guard needs no jax work and is safe in-process
    import raft_tpu
    from raft_tpu.core import RaftError

    with pytest.raises(RaftError, match="dtype must be"):
        raft_tpu.warmup("ivf_flat", n=100, d=8, dtype="float16")


def test_warmup_accepts_user_data_sample(tmp_path):
    """warmup(data=...) builds/searches on rows resampled from the user's
    sample (VERDICT r5 #5: uniform random data is the data-adaptive builds'
    measured worst case — 483 s vs ~130 s for cagra at 1M), keeping shapes
    (and therefore the warmed program set) identical. Subprocess for the
    same cache-redirect reason as test_warmup_entry_point."""
    import subprocess
    import sys
    from pathlib import Path

    repo = Path(__file__).resolve().parents[1]
    cache = tmp_path / "warmcache_sample"
    code = f"""
import sys
sys.path.insert(0, {str(repo)!r})
from raft_tpu.core.platform import force_virtual_cpu
force_virtual_cpu(1)
import numpy as np
import raft_tpu
from raft_tpu.neighbors import ivf_flat
rng = np.random.default_rng(0)
centers = rng.random((8, 16)).astype(np.float32) * 10
sample = (centers[rng.integers(0, 8, 300)]
          + rng.normal(0, 0.3, (300, 16)).astype(np.float32))
out = raft_tpu.warmup("ivf_flat", n=2000, d=16, queries=64, data=sample,
                      index_params=ivf_flat.IndexParams(n_lists=16, seed=0),
                      cache_dir={str(cache)!r})
assert out["build_s"] > 0 and out["search_s"] > 0, out
# int8 sample: dtype inferred from the sample bytes
i8 = rng.integers(-128, 128, (300, 16)).astype(np.int8)
out = raft_tpu.warmup("brute_force", n=500, d=16, queries=32, data=i8,
                      cache_dir={str(cache)!r})
print("WARMUP_SAMPLE_OK")
"""
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, timeout=360)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "WARMUP_SAMPLE_OK" in r.stdout

    # shape/dtype validation needs no cache and is safe in-process
    import numpy as np

    import raft_tpu
    from raft_tpu.core import RaftError

    with pytest.raises(RaftError, match="data sample must be"):
        raft_tpu.warmup("ivf_flat", n=100, d=8,
                        data=np.zeros((10, 9), np.float32))
    with pytest.raises(RaftError, match="dtype"):
        raft_tpu.warmup("ivf_flat", n=100, d=8, dtype="int8",
                        data=np.zeros((10, 8), np.float32))
