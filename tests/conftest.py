"""Test configuration.

Mirrors the reference's test topology (SURVEY.md §4): numerical tests run on
CPU with an 8-device virtual platform so multi-chip sharding is exercised
without TPU hardware — the analogue of the reference's LocalCUDACluster-based
comms tests (python/raft-dask/raft_dask/test/test_comms.py) and its
per-namespace gtest binaries. Environment variables must be set before the
first jax import.
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from raft_tpu.core.platform import force_virtual_cpu  # noqa: E402

force_virtual_cpu(8)

import jax  # noqa: E402
import numpy as np  # noqa: E402
import pytest  # noqa: E402

# Fail loudly if something initialized the backend before the force landed —
# otherwise single-device tests would silently run on the ambient TPU platform.
assert jax.default_backend() == "cpu" and len(jax.devices()) >= 8, (
    f"platform force failed: backend={jax.default_backend()} devices={len(jax.devices())}"
)

jax.config.update("jax_enable_x64", False)


@pytest.fixture(scope="session")
def devices():
    return jax.devices()


@pytest.fixture(scope="session")
def mesh8(devices):
    """An 8-device 1-D mesh over the virtual CPU platform."""
    from jax.sharding import Mesh

    assert len(devices) >= 8, "conftest must force 8 host devices"
    return Mesh(np.array(devices[:8]), ("data",))


@pytest.fixture
def res():
    from raft_tpu.core import Resources

    return Resources()


@pytest.fixture
def rng():
    return np.random.default_rng(42)


@pytest.fixture
def check_filter_underfill():
    """Shared filtered-search underfill contract (ISSUE 5 satellite): when
    fewer than k rows survive a sample filter, every neighbors module must
    report the surviving rows first (finite scores, real ids) and fill the
    rest with id -1 at +inf (L2) / -inf (inner product) — one checker so
    the four modules cannot drift apart."""

    def check(dists, ids, expected_alive, select_min=True):
        d, i = np.asarray(dists), np.asarray(ids)
        alive = sorted(expected_alive)
        n_alive = len(alive)
        bad = np.inf if select_min else -np.inf
        if n_alive >= i.shape[1]:
            # enough survivors to fill every slot: no sentinel may appear
            # and every id must come from the alive set — a pre-filter
            # tier (e.g. the ivf_pq funnel's binary stage) that silently
            # narrowed the candidate pool would underfill or leak here
            assert (i >= 0).all(), i
            assert np.isfinite(d).all(), d
            assert set(i.ravel().tolist()) <= set(alive), i
            return
        assert (i[:, n_alive:] == -1).all(), i
        assert (d[:, n_alive:] == bad).all(), d
        assert np.isfinite(d[:, :n_alive]).all(), d
        for row in i[:, :n_alive]:
            assert sorted(row.tolist()) == alive, (row, alive)

    return check


def pytest_collection_modifyitems(config, items):
    """Apply the slow marker from tests/slow_tests.txt (measured durations on
    the CPU mesh — see pytest.ini). The fast tier is `pytest -m "not slow"`."""
    from pathlib import Path

    listed = {
        line.strip()
        for line in (Path(__file__).parent / "slow_tests.txt").read_text().splitlines()
        if line.strip() and not line.startswith("#")
    }
    collected = {item.nodeid for item in items}
    # Hard-fail on rot (VERDICT r2 weak #7): a listed nodeid is stale when its
    # test FILE was collected but the test wasn't (renamed/deleted test), or
    # the file itself is gone. Scoped per-file so running a single test file
    # doesn't flag the others; -k runs are exempt (they filter collection).
    collected_files = {item.nodeid.split("::")[0] for item in items}
    root = Path(__file__).resolve().parents[1]
    stale = {
        nid for nid in listed - collected
        if nid.split("::")[0] in collected_files
        or not (root / nid.split("::")[0]).exists()
    }
    # -k runs and explicit nodeid selections (pytest file::test) collect only
    # a slice of a file — sibling listed tests would read as falsely stale
    selective = config.option.keyword or any("::" in a for a in config.args)
    if stale and not selective:
        raise pytest.UsageError(
            f"tests/slow_tests.txt lists {len(stale)} nodeid(s) that no "
            f"longer exist (renamed tests silently join the fast tier) — "
            f"update the list: {sorted(stale)[:5]}")
    for item in items:
        if item.nodeid in listed:
            item.add_marker(pytest.mark.slow)
