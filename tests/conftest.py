"""Test configuration.

Mirrors the reference's test topology (SURVEY.md §4): numerical tests run on
CPU with an 8-device virtual platform so multi-chip sharding is exercised
without TPU hardware — the analogue of the reference's LocalCUDACluster-based
comms tests (python/raft-dask/raft_dask/test/test_comms.py) and its
per-namespace gtest binaries. Environment variables must be set before the
first jax import.
"""

import os

import re

_flags = os.environ.get("XLA_FLAGS", "")
_flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "", _flags)
os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402
import numpy as np  # noqa: E402
import pytest  # noqa: E402

# Force CPU: the ambient environment pins JAX to the single-chip TPU tunnel;
# tests want 8 virtual devices. jax is already imported by the interpreter's
# sitecustomize, so the env var route is too late — use the config API, which
# works any time before backend initialization.
jax.config.update("jax_platforms", "cpu")

jax.config.update("jax_enable_x64", False)


@pytest.fixture(scope="session")
def devices():
    return jax.devices()


@pytest.fixture(scope="session")
def mesh8(devices):
    """An 8-device 1-D mesh over the virtual CPU platform."""
    from jax.sharding import Mesh

    assert len(devices) >= 8, "conftest must force 8 host devices"
    return Mesh(np.array(devices[:8]), ("data",))


@pytest.fixture
def res():
    from raft_tpu.core import Resources

    return Resources()


@pytest.fixture
def rng():
    return np.random.default_rng(42)
