"""CAGRA tests — recall acceptance vs brute force (reference analogue:
cpp/test/neighbors/ann_cagra.cuh)."""

import numpy as np
import pytest
from scipy.spatial import distance as sp_dist

from raft_tpu.neighbors import cagra
from raft_tpu.random import make_blobs


def _recall(got_ids, true_ids):
    hits = 0
    for g, t in zip(got_ids, true_ids):
        hits += len(set(g.tolist()) & set(t.tolist()))
    return hits / true_ids.size


@pytest.fixture(scope="module")
def data():
    # uniform data, like real ANN benchmark distributions: on well-separated
    # blobs a kNN graph has no inter-cluster edges, so graph traversal cannot
    # cross clusters lacking an entry point (the reference's CAGRA has the
    # same property — it's inherent to graph ANN, not an implementation bug)
    rng = np.random.default_rng(0)
    x = rng.random((4000, 24)).astype(np.float32)
    q = rng.random((60, 24)).astype(np.float32)
    return x, q


@pytest.fixture(scope="module")
def index(data):
    x, _ = data
    return cagra.build(
        cagra.IndexParams(intermediate_graph_degree=48, graph_degree=24, seed=0), x
    )


class TestBuild:
    def test_graph_shape_and_validity(self, index, data):
        x, _ = data
        g = np.asarray(index.graph)
        assert g.shape == (4000, 24)
        assert g.min() >= 0 and g.max() < 4000
        # no self-edges
        assert not (g == np.arange(4000)[:, None]).any()

    def test_knn_graph_quality(self, data):
        """Intermediate kNN graph edges should largely be true neighbors."""
        x, _ = data
        params = cagra.IndexParams(intermediate_graph_degree=16, graph_degree=8, seed=0)
        g = np.asarray(cagra.build_knn_graph(params, x))
        true_i = np.argsort(sp_dist.cdist(x[:200], x, "sqeuclidean"), 1)[:, 1:17]
        rec = _recall(g[:200], true_i)
        assert rec > 0.8, rec

    def test_optimize_degree(self, data):
        x, _ = data
        params = cagra.IndexParams(intermediate_graph_degree=32, graph_degree=16, seed=0)
        g = cagra.build_knn_graph(params, x)
        opt = np.asarray(cagra.optimize(g, 16))
        assert opt.shape == (4000, 16)
        assert opt.min() >= 0


class TestSearch:
    def test_recall(self, index, data):
        x, q = data
        d, i = cagra.search(cagra.SearchParams(itopk_size=64), index, q, k=10)
        true_i = np.argsort(sp_dist.cdist(q, x, "sqeuclidean"), 1)[:, :10]
        rec = _recall(np.asarray(i), true_i)
        assert rec > 0.9, rec

    def test_distances_are_exact_for_found_ids(self, index, data):
        x, q = data
        d, i = cagra.search(cagra.SearchParams(itopk_size=64), index, q, k=5)
        full = sp_dist.cdist(q, x, "sqeuclidean")
        got = np.take_along_axis(full, np.asarray(i), 1)
        np.testing.assert_allclose(np.asarray(d), got, atol=1e-2, rtol=1e-3)

    def test_wider_beam_improves_recall(self, index, data):
        x, q = data
        true_i = np.argsort(sp_dist.cdist(q, x, "sqeuclidean"), 1)[:, :10]
        recalls = []
        for itopk in (16, 64, 128):
            _, i = cagra.search(cagra.SearchParams(itopk_size=itopk), index, q, k=10)
            recalls.append(_recall(np.asarray(i), true_i))
        assert recalls[-1] >= recalls[0]
        assert recalls[-1] > 0.95, recalls

    def test_search_width(self, index, data):
        x, q = data
        true_i = np.argsort(sp_dist.cdist(q, x, "sqeuclidean"), 1)[:, :10]
        _, i = cagra.search(cagra.SearchParams(itopk_size=64, search_width=4), index, q, k=10)
        assert _recall(np.asarray(i), true_i) > 0.9


class TestSerialize:
    def test_roundtrip(self, tmp_path, index, data):
        _, q = data
        p = str(tmp_path / "cagra.bin")
        cagra.save(index, p)
        idx2 = cagra.load(p)
        d1, i1 = cagra.search(cagra.SearchParams(itopk_size=32), index, q, k=5)
        d2, i2 = cagra.search(cagra.SearchParams(itopk_size=32), idx2, q, k=5)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))


class TestSeedPool:
    def test_seeded_entries_on_clustered_data(self):
        """Scored seed-pool entries must recover recall on well-separated
        clusters, where purely random entries (seed_pool=0, the reference's
        seeding) start in the wrong basin and the pruned graph has no
        cross-cluster edges to escape through."""
        x, _ = make_blobs(3000, 24, n_clusters=30, cluster_std=0.5, seed=2)
        x = np.asarray(x)
        idx = cagra.build(
            cagra.IndexParams(intermediate_graph_degree=24, graph_degree=12, seed=0), x
        )
        q = x[:150]
        true_i = np.argsort(sp_dist.cdist(q, x, "sqeuclidean"), 1)[:, :10]
        _, i_seeded = cagra.search(cagra.SearchParams(itopk_size=32), idx, q, k=10)
        rec_seeded = _recall(np.asarray(i_seeded), true_i)
        _, i_rand = cagra.search(cagra.SearchParams(itopk_size=32, seed_pool=0), idx, q, k=10)
        rec_rand = _recall(np.asarray(i_rand), true_i)
        assert rec_seeded > 0.9, (rec_seeded, rec_rand)
        assert rec_seeded >= rec_rand

    def test_search_seed_contract(self, index, data):
        """Same seed → bitwise-identical results; a different seed draws a
        different entry pool (VERDICT r3 weak #3) but stays a valid search."""
        x, q = data
        sp0 = cagra.SearchParams(itopk_size=32, seed=0)
        d1, i1 = cagra.search(sp0, index, q, k=10)
        d2, i2 = cagra.search(sp0, index, q, k=10)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
        np.testing.assert_array_equal(np.asarray(d1), np.asarray(d2))
        _, i3 = cagra.search(cagra.SearchParams(itopk_size=32, seed=3),
                             index, q, k=10)
        true_i = np.argsort(sp_dist.cdist(q, x, "sqeuclidean"), 1)[:, :10]
        assert _recall(np.asarray(i3), true_i) > 0.9


class TestFusedHop:
    """The fused Pallas hop kernel (ops/cagra_hop.py, VERDICT r4 #1) must
    reproduce the XLA hop loop: same beam semantics (ascending dedup merge,
    lowest-id ties, visited tracking), so same neighbor sets and distances
    up to summation order."""

    @pytest.mark.parametrize("impl", ["fused", "fused_arena", "fused_arena_smem"])
    def test_matches_xla_loop(self, index, data, monkeypatch, impl):
        monkeypatch.setenv("RAFT_TPU_CAGRA_HOP_INTERPRET", "1")
        x, q = data
        d_x, i_x = cagra.search(
            cagra.SearchParams(itopk_size=32, hop_impl="xla"), index, q, k=10)
        d_f, i_f = cagra.search(
            cagra.SearchParams(itopk_size=32, hop_impl=impl), index, q, k=10)
        i_x, i_f = np.asarray(i_x), np.asarray(i_f)
        # id sets match except where summation-order ULP noise reorders
        # near-ties at the beam boundary
        overlap = np.mean([len(set(i_x[r]) & set(i_f[r])) / 10
                           for r in range(i_x.shape[0])])
        assert overlap > 0.99, overlap
        np.testing.assert_allclose(np.sort(np.asarray(d_f), 1),
                                   np.sort(np.asarray(d_x), 1),
                                   rtol=1e-4, atol=1e-4)

    @pytest.mark.parametrize("impl", ["fused", "fused_arena", "fused_arena_smem"])
    def test_recall_on_clustered(self, monkeypatch, impl):
        monkeypatch.setenv("RAFT_TPU_CAGRA_HOP_INTERPRET", "1")
        x, _ = make_blobs(3000, 24, n_clusters=30, cluster_std=0.5, seed=2)
        x = np.asarray(x)
        idx = cagra.build(cagra.IndexParams(
            intermediate_graph_degree=24, graph_degree=12, seed=0), x)
        q = x[:150]
        true_i = np.argsort(sp_dist.cdist(q, x, "sqeuclidean"), 1)[:, :10]
        _, ids = cagra.search(cagra.SearchParams(
            itopk_size=32, hop_impl=impl), idx, q, k=10)
        rec = _recall(np.asarray(ids), true_i)
        assert rec > 0.9, rec

    def test_fused_sqrt_metric(self, data, monkeypatch):
        monkeypatch.setenv("RAFT_TPU_CAGRA_HOP_INTERPRET", "1")
        import dataclasses

        from raft_tpu.distance.types import DistanceType

        x, q = data
        idx = cagra.build(cagra.IndexParams(
            intermediate_graph_degree=24, graph_degree=12,
            metric="euclidean", seed=0), x)
        assert idx.metric in (DistanceType.L2SqrtExpanded,
                              DistanceType.L2SqrtUnexpanded)
        d_f, i_f = cagra.search(cagra.SearchParams(
            itopk_size=32, hop_impl="fused_arena"), idx, q, k=5)
        d_true = np.sqrt(((q[:, None, :] - x[np.asarray(i_f)]) ** 2).sum(-1))
        np.testing.assert_allclose(np.asarray(d_f), d_true, rtol=1e-4,
                                   atol=1e-4)

    @pytest.mark.parametrize("impl", ["fused", "fused_arena", "fused_arena_smem"])
    def test_matches_xla_loop_width2(self, index, data, monkeypatch, impl):
        """search_width=2: two picks per hop, candidate block 2*deg — must
        still track the XLA loop."""
        monkeypatch.setenv("RAFT_TPU_CAGRA_HOP_INTERPRET", "1")
        _, q = data
        d_x, i_x = cagra.search(cagra.SearchParams(
            itopk_size=32, search_width=2, hop_impl="xla"), index, q, k=10)
        d_f, i_f = cagra.search(cagra.SearchParams(
            itopk_size=32, search_width=2, hop_impl=impl), index, q, k=10)
        i_x, i_f = np.asarray(i_x), np.asarray(i_f)
        overlap = np.mean([len(set(i_x[r]) & set(i_f[r])) / 10
                           for r in range(i_x.shape[0])])
        assert overlap > 0.95, overlap
        np.testing.assert_allclose(np.sort(np.asarray(d_f), 1),
                                   np.sort(np.asarray(d_x), 1),
                                   rtol=1e-4, atol=1e-4)

    def test_eligibility_guard(self, index, data):
        from raft_tpu.core import RaftError

        _, q = data
        # itopk 64 + 3*24 = 136 > 128: pool does not fit one register row
        with pytest.raises(RaftError, match="hop_impl='fused'"):
            cagra.search(cagra.SearchParams(
                itopk_size=64, search_width=3, hop_impl="fused"),
                index, q, k=5)


class TestSeedPoolAuto:
    """The measured seed_pool autotune (VERDICT r4 #4): the build reads the
    clump scale off the knn graph's neighbor-distance jump profile and sizes
    the entry pool to the local-mode count."""

    @staticmethod
    def _clumpy(n_clumps, clump, d, scale, rng):
        centers = rng.random((n_clumps, d)).astype(np.float32)
        x = (np.repeat(centers, clump, axis=0)
             + scale * rng.standard_normal((n_clumps * clump, d))
             .astype(np.float32))
        return x

    def test_detects_clumps_and_sizes_pool(self):
        """65536 points in 16384 4-point near-duplicate clumps, knn graph =
        3 clump-mates + 5 far points: jump at position 3 → ~16k modes →
        pool 32768 (> the 16384 default the isotropic path keeps)."""
        rng = np.random.default_rng(0)
        n_clumps, clump, d = 16384, 4, 8
        x = self._clumpy(n_clumps, clump, d, 1e-3, rng)
        n = n_clumps * clump
        i = np.arange(n)
        mates = (i // clump)[:, None] * clump + np.arange(clump)[None, :]
        mates = np.stack(
            [mates[r][mates[r] != r] for r in range(0, n)], axis=0)
        far = rng.integers(0, n, (n, 5))
        g = np.concatenate([mates, far], axis=1).astype(np.int32)
        pool = cagra.estimate_seed_pool(x, g, seed=0)
        assert pool == 32768, pool

    def test_isotropic_keeps_default(self):
        """Uniform data + random graph: no >=2x jump — hint 0 (default pool;
        a bigger pool on isotropic data is a pure QPS loss, r02)."""
        rng = np.random.default_rng(1)
        n, d = 8192, 16
        x = rng.random((n, d)).astype(np.float32)
        g = rng.integers(0, n, (n, 8)).astype(np.int32)
        assert cagra.estimate_seed_pool(x, g, seed=0) == 0

    def test_small_modes_keep_default(self):
        """Clumpy but few modes: 2*modes <= 16384 — the default pool already
        covers them, hint stays 0."""
        rng = np.random.default_rng(2)
        x = self._clumpy(512, 16, 8, 1e-3, rng)
        n = 512 * 16
        i = np.arange(n)
        mates = (i // 16)[:, None] * 16 + np.arange(16)[None, :]
        mates = np.stack(
            [mates[r][mates[r] != r][:7] for r in range(n)], axis=0)
        far = rng.integers(0, n, (n, 5))
        g = np.concatenate([mates, far], axis=1).astype(np.int32)
        assert cagra.estimate_seed_pool(x, g, seed=0) == 0

    def test_auto_resolves_to_hint(self, index, data):
        """seed_pool=-1 (default) must search exactly like an explicit pool
        equal to the index hint."""
        import dataclasses

        _, q = data
        idx2 = dataclasses.replace(index, seed_pool_hint=2048)
        d_auto, i_auto = cagra.search(
            cagra.SearchParams(itopk_size=32), idx2, q, k=10)
        d_exp, i_exp = cagra.search(
            cagra.SearchParams(itopk_size=32, seed_pool=2048), index, q, k=10)
        np.testing.assert_array_equal(np.asarray(i_auto), np.asarray(i_exp))
        np.testing.assert_array_equal(np.asarray(d_auto), np.asarray(d_exp))

    def test_hint_survives_serialization(self, tmp_path, index):
        import dataclasses

        idx2 = dataclasses.replace(index, seed_pool_hint=32768)
        p = str(tmp_path / "cagra_hint.bin")
        cagra.save(idx2, p)
        assert cagra.load(p).seed_pool_hint == 32768


class TestBuildProbesAuto:
    def test_auto_adopts_cheap_probes_on_clustered_data(self, caplog):
        """The measured build_n_probes auto (chunk-0 p=32 vs p=8/16 edge
        overlap) must adopt a cheap setting on clustered data — where the
        full-build A/B showed identical recall — and keep the graph good."""
        import logging

        x, _ = make_blobs(3000, 24, n_clusters=30, cluster_std=0.5, seed=4)
        x = np.asarray(x)
        params = cagra.IndexParams(
            intermediate_graph_degree=16, graph_degree=8,
            build_chunk=1000, seed=0)
        with caplog.at_level(logging.INFO, logger="raft_tpu"):
            g = np.asarray(cagra.build_knn_graph(params, x))
        assert g.shape == (3000, 16)
        assert any("build_n_probes auto" in r.message for r in caplog.records)
        true_i = np.argsort(sp_dist.cdist(x[:200], x, "sqeuclidean"), 1)[:, 1:17]
        rec = _recall(g[:200], true_i)
        assert rec > 0.8, rec


class TestByteDatasets:
    """int8/uint8 datasets end-to-end (reference: the dtype-generic
    cagra::index<T> int8_t/uint8_t instantiations). The index stores native
    bytes — uint8 shifted by -128 into the s8 domain, L2-invariant — and the
    hop paths upcast to f32 at the tile level, where every 8-bit integer is
    exact, so byte results are checked against the f64 image of the same
    bytes rather than a loosened threshold."""

    @pytest.fixture(scope="class")
    def idata(self):
        # uniform bytes (see the module fixture's note on blobs vs graphs)
        rng = np.random.default_rng(11)
        xu = rng.integers(0, 256, (3000, 24), dtype=np.uint8)
        qu = rng.integers(0, 256, (50, 24), dtype=np.uint8)
        return xu, qu

    @pytest.fixture(scope="class")
    def u8_index(self, idata):
        xu, _ = idata
        return cagra.build(cagra.IndexParams(
            intermediate_graph_degree=48, graph_degree=24, seed=0), xu)

    def test_native_byte_storage(self, u8_index):
        import jax.numpy as jnp

        assert u8_index.data_kind == "uint8"
        assert u8_index.dataset.dtype == jnp.int8  # shifted s8 bytes

    def test_recall_and_exact_distances(self, u8_index, idata):
        xu, qu = idata
        d, i = cagra.search(cagra.SearchParams(itopk_size=64), u8_index, qu, k=10)
        d2 = ((qu[:, None, :].astype(np.float64)
               - xu[None].astype(np.float64)) ** 2).sum(-1)
        true_i = np.argsort(d2, 1)[:, :10]
        rec = _recall(np.asarray(i), true_i)
        assert rec > 0.9, rec
        # the -128 shift is L2-invariant and 8-bit values are exact in f32:
        # reported distances are the true integer byte-domain distances
        got = np.take_along_axis(d2, np.asarray(i), 1)
        np.testing.assert_allclose(np.asarray(d), got, rtol=1e-6)

    def test_int8_matches_uint8_shifted(self, u8_index, idata):
        """uint8 ingestion = the pre-shifted int8 build, bit for bit."""
        xu, qu = idata
        xs = (xu.astype(np.int16) - 128).astype(np.int8)
        qs = (qu.astype(np.int16) - 128).astype(np.int8)
        idx = cagra.build(cagra.IndexParams(
            intermediate_graph_degree=48, graph_degree=24, seed=0), xs)
        assert idx.data_kind == "int8"
        np.testing.assert_array_equal(np.asarray(idx.graph),
                                      np.asarray(u8_index.graph))
        _, i_s = cagra.search(cagra.SearchParams(itopk_size=64), idx, qs, k=10)
        _, i_u = cagra.search(cagra.SearchParams(itopk_size=64), u8_index, qu, k=10)
        np.testing.assert_array_equal(np.asarray(i_s), np.asarray(i_u))

    def test_float_queries_on_uint8_index(self, u8_index, idata):
        _, qu = idata
        _, i_b = cagra.search(cagra.SearchParams(itopk_size=64), u8_index, qu, k=10)
        _, i_f = cagra.search(cagra.SearchParams(itopk_size=64), u8_index,
                              qu.astype(np.float32), k=10)
        np.testing.assert_array_equal(np.asarray(i_b), np.asarray(i_f))

    def test_query_dtype_guard(self, u8_index, idata):
        from raft_tpu.core import RaftError

        _, qu = idata
        qs = (qu.astype(np.int16) - 128).astype(np.int8)
        with pytest.raises(RaftError, match="stores uint8"):
            cagra.search(cagra.SearchParams(itopk_size=64), u8_index, qs, k=10)

    def test_fused_hop_matches_xla_on_bytes(self, u8_index, idata, monkeypatch):
        """The Pallas hop takes int8 candidate blocks (quarter the DMA
        bytes) and upcasts in-kernel — must track the XLA loop."""
        monkeypatch.setenv("RAFT_TPU_CAGRA_HOP_INTERPRET", "1")
        _, qu = idata
        d_x, i_x = cagra.search(cagra.SearchParams(
            itopk_size=32, hop_impl="xla"), u8_index, qu, k=10)
        d_f, i_f = cagra.search(cagra.SearchParams(
            itopk_size=32, hop_impl="fused_arena"), u8_index, qu, k=10)
        i_x, i_f = np.asarray(i_x), np.asarray(i_f)
        overlap = np.mean([len(set(i_x[r]) & set(i_f[r])) / 10
                           for r in range(i_x.shape[0])])
        assert overlap > 0.95, overlap
        np.testing.assert_allclose(np.sort(np.asarray(d_f), 1),
                                   np.sort(np.asarray(d_x), 1),
                                   rtol=1e-4, atol=1e-4)

    def test_roundtrip_preserves_bytes(self, tmp_path, u8_index, idata):
        import jax.numpy as jnp

        _, qu = idata
        p = str(tmp_path / "cagra_u8.bin")
        cagra.save(u8_index, p)
        idx2 = cagra.load(p)
        assert idx2.data_kind == "uint8"
        assert idx2.dataset.dtype == jnp.int8
        d1, i1 = cagra.search(cagra.SearchParams(itopk_size=32), u8_index, qu, k=5)
        d2, i2 = cagra.search(cagra.SearchParams(itopk_size=32), idx2, qu, k=5)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
        np.testing.assert_array_equal(np.asarray(d1), np.asarray(d2))


def test_seed_pool_log_reports_calibrated_threshold(caplog):
    """The seed_pool autotune logs must interpolate the threshold constant
    actually applied (_SEED_JUMP_RATIO = 2.0), not a stale literal
    (ADVICE r5: the success log said "jump >=4x" while the rule was 2.0)."""
    import logging

    rng = np.random.default_rng(0)
    # >= 4096 rows and >= 8 graph columns: the autotune's lower bound —
    # smaller inputs return the default pool without logging
    x = rng.random((4200, 16)).astype(np.float32)
    params = cagra.IndexParams(intermediate_graph_degree=16, graph_degree=8,
                               build_chunk=2100, seed=0)
    g = cagra.build_knn_graph(params, np.asarray(x))
    with caplog.at_level(logging.INFO, logger="raft_tpu"):
        cagra.estimate_seed_pool(x, g, seed=0)
    msgs = [r.getMessage() for r in caplog.records
            if "seed_pool auto" in r.getMessage()]
    assert msgs, "autotune logged nothing"
    want = ">=%.0fx" % cagra._SEED_JUMP_RATIO
    assert all("4x" not in m or want == ">=4x" for m in msgs), msgs
    assert any(want in m for m in msgs), (want, msgs)


@pytest.mark.slow
def test_shard_local_vs_global_graph_recall_64k():
    """VERDICT r5 item 10: quantify the recall cost of shard-local CAGRA
    graphs (parallel.cagra.build — one independent graph per dataset shard,
    merged over ICI at search) vs ONE global graph over the same rows, at a
    realistic scale on the 8-device mesh: 64k rows / 8 shards of 8k.

    Expectation (docs/using_comms.md "Shard-local CAGRA graphs" records the
    measured numbers): the merged result's recall does NOT degrade vs the
    global graph — each true neighbor lives in exactly one shard, the beam
    searches its 8x-smaller graph with the SAME itopk (an easier problem),
    and the allgather+select_k merge is exact over the per-shard top-k. The
    cost is compute (S beams per query + the merge), not recall; per-shard
    graphs stop being acceptable only when a shard falls below the point
    where graph search beats brute force (~thousands of rows), not for
    recall reasons.
    """
    import jax
    from jax.sharding import Mesh

    from raft_tpu.comms.comms import Comms
    from raft_tpu.neighbors import brute_force
    from raft_tpu.parallel import cagra as pcagra

    n, d, m, k = 65536, 64, 256, 10
    rng = np.random.default_rng(7)
    # clustered (the regime where entry-point coverage matters; uniform data
    # would hide shard effects behind an easy neighbor structure)
    centers = rng.random((256, d)).astype(np.float32) * 10.0
    lab = rng.integers(0, 256, n)
    x = (centers[lab] + 0.5 * rng.standard_normal((n, d))).astype(np.float32)
    qlab = rng.integers(0, 256, m)
    q = (centers[qlab] + 0.5 * rng.standard_normal((m, d))).astype(np.float32)

    _, gt = brute_force.knn(x, q, k)
    gt = np.asarray(gt)

    params = cagra.IndexParams(seed=0)
    sp = cagra.SearchParams(itopk_size=32)

    g_idx = cagra.build(params, x)
    _, g_ids = cagra.search(sp, g_idx, q, k)
    recall_global = _recall(np.asarray(g_ids), gt)

    comms = Comms(Mesh(np.array(jax.devices()[:8]), ("data",)), "data")
    s_idx = pcagra.build(comms, params, x)
    assert s_idx.n_shards == 8 and s_idx.rows_per_shard == n // 8
    _, s_ids = pcagra.search(comms, sp, s_idx, q, k)
    recall_sharded = _recall(np.asarray(s_ids), gt)

    # sanity floors + the documented relationship: shard-local graphs hold
    # recall at this scale (gap bound loose enough for seed noise; the
    # measured r06 gap is recorded in docs/using_comms.md)
    assert recall_global > 0.85, recall_global
    assert recall_sharded > 0.85, recall_sharded
    assert recall_sharded >= recall_global - 0.03, (
        recall_sharded, recall_global)


@pytest.mark.slow
def test_build_select_impl_pallas_matches_xla():
    """IndexParams.build_select_impl routes the build self-search's
    k = gpu_top_k + 1 candidate selects through the wide-k Pallas selector
    (the r05-commissioned call site, VERDICT r5 #3). Both impls must produce
    the IDENTICAL knn graph — the selector is exact with lax.top_k tie
    semantics — and this exercises the two-wide-instances-per-program
    composition (per-chunk + final merge) end to end through ivf_pq."""
    rng = np.random.default_rng(5)
    x = np.asarray(make_blobs(800, 16, n_clusters=10, cluster_std=0.6,
                              seed=3)[0])
    graphs = {}
    for impl in ("xla", "pallas"):
        params = cagra.IndexParams(
            intermediate_graph_degree=48, graph_degree=16, refine_rate=2.0,
            build_n_probes=8, build_chunk=800, build_select_impl=impl,
            seed=0)
        graphs[impl] = np.asarray(cagra.build_knn_graph(params, x))
    np.testing.assert_array_equal(graphs["xla"], graphs["pallas"])


class TestSampleFilter:
    """`sample_filter=` parity with brute_force/ivf_pq (ISSUE 5 satellite):
    mask epilogue on candidate scores before the beam select, same
    resolve_filter/validate_filter_covers contract, shared -1/+inf
    underfill sentinel."""

    def test_filtered_matches_filtered_brute_force(self, index, data):
        from raft_tpu.neighbors import brute_force

        x, q = data
        keep = np.ones(x.shape[0], bool)
        keep[::2] = False  # drop half the rows
        d, i = cagra.search(cagra.SearchParams(itopk_size=64), index, q, 10,
                            sample_filter=keep)
        i = np.asarray(i)
        assert (i[i >= 0] % 2 == 1).all()  # only kept rows surface
        _, ref = brute_force.knn(x, q, 10, sample_filter=keep)
        assert _recall(i, np.asarray(ref)) > 0.9

    def test_bitset_filter_object(self, index, data):
        from raft_tpu.neighbors import BitsetFilter

        x, q = data
        keep = np.zeros(x.shape[0], bool)
        keep[:100] = True
        _, i = cagra.search(cagra.SearchParams(itopk_size=64), index, q, 10,
                            sample_filter=BitsetFilter(keep))
        i = np.asarray(i)
        assert ((i < 100) | (i == -1)).all()

    def test_underfill_sentinels(self, index, data, check_filter_underfill):
        x, q = data
        alive = [5, 77, 1234]
        keep = np.zeros(x.shape[0], bool)
        keep[alive] = True
        d, i = cagra.search(cagra.SearchParams(itopk_size=64), index, q, 10,
                            sample_filter=keep)
        check_filter_underfill(d, i, alive, select_min=True)

    def test_filter_cover_validated(self, index, data):
        from raft_tpu.core.errors import RaftError

        x, q = data
        with pytest.raises(RaftError, match="cover"):
            cagra.search(cagra.SearchParams(), index, q, 10,
                         sample_filter=np.ones(x.shape[0] - 1, bool))

    @pytest.mark.parametrize("impl", ["fused_arena"])
    def test_fused_hop_filter_matches_xla(self, index, data, monkeypatch,
                                          impl):
        monkeypatch.setenv("RAFT_TPU_CAGRA_HOP_INTERPRET", "1")
        x, q = data
        keep = np.ones(x.shape[0], bool)
        keep[:x.shape[0] // 2] = False
        d_x, i_x = cagra.search(
            cagra.SearchParams(itopk_size=32, hop_impl="xla"), index, q, 10,
            sample_filter=keep)
        d_f, i_f = cagra.search(
            cagra.SearchParams(itopk_size=32, hop_impl=impl), index, q, 10,
            sample_filter=keep)
        i_x, i_f = np.asarray(i_x), np.asarray(i_f)
        assert (i_f[i_f >= 0] >= x.shape[0] // 2).all()
        overlap = np.mean([len(set(i_x[r]) & set(i_f[r])) / 10
                           for r in range(i_x.shape[0])])
        assert overlap > 0.95, overlap


class TestMergedShardedBuild:
    """parallel.cagra.build_merged (ISSUE 6): per-shard graphs concatenated
    into ONE plain CagraIndex — every single-chip consumer takes it
    unchanged, and the scored seed pool (spanning all shards) keeps recall
    at parity with a global build (the r06 64k/8 measured result)."""

    @pytest.fixture(scope="class")
    def mdata(self):
        rng = np.random.default_rng(3)
        centers = rng.random((16, 16)).astype(np.float32) * 10
        lab = rng.integers(0, 16, 2000)
        x = (centers[lab] + 0.3 * rng.standard_normal((2000, 16))).astype(
            np.float32)
        return x

    @pytest.fixture(scope="class")
    def merged(self, mdata):
        import jax
        from jax.sharding import Mesh

        from raft_tpu.comms.comms import Comms
        from raft_tpu.parallel import cagra as pcagra

        comms = Comms(Mesh(np.array(jax.devices()[:8]), ("data",)), "data")
        params = cagra.IndexParams(intermediate_graph_degree=16,
                                   graph_degree=8, build_chunk=1024, seed=0)
        return pcagra.build_merged(comms, params, mdata)

    def test_structure_and_shard_locality(self, mdata, merged):
        from raft_tpu.parallel import cagra as pcagra

        n = mdata.shape[0]
        assert merged.dataset.shape == (n, 16)
        assert merged.graph.shape == (n, 8)
        g = np.asarray(merged.graph)
        assert g.min() >= 0 and g.max() < n
        # uneven shards allowed (2000 / 8 = 250): edges stay within their
        # owning shard's global row range — no cross-shard edges by
        # construction
        for lo, hi in pcagra._shard_bounds(n, 8):
            assert g[lo:hi].min() >= lo and g[lo:hi].max() < hi, (lo, hi)
        # the merged dataset preserves the original row order
        np.testing.assert_array_equal(np.asarray(merged.dataset), mdata)

    def test_search_recall_parity_vs_single(self, mdata, merged):
        from raft_tpu.neighbors import brute_force

        params = cagra.IndexParams(intermediate_graph_degree=16,
                                   graph_degree=8, build_chunk=1024, seed=0)
        single = cagra.build(params, mdata)
        q = mdata[:64]
        _, gt = brute_force.knn(mdata, q, 5)
        gt = np.asarray(gt)
        sp = cagra.SearchParams(itopk_size=16)

        def rec(idx):
            _, ids = cagra.search(sp, idx, q, 5)
            return _recall(np.asarray(ids), gt)

        r_merged, r_single = rec(merged), rec(single)
        assert r_merged > 0.8, r_merged
        assert r_merged >= r_single - 0.03, (r_merged, r_single)

    def test_uneven_rows_and_degree_bound(self, mdata):
        import jax
        from jax.sharding import Mesh

        from raft_tpu.comms.comms import Comms
        from raft_tpu.core import RaftError
        from raft_tpu.parallel import cagra as pcagra

        comms = Comms(Mesh(np.array(jax.devices()[:8]), ("data",)), "data")
        # 2001 rows over 8 shards: bounds cover every row exactly once
        bounds = pcagra._shard_bounds(2001, 8)
        assert bounds[0] == (0, 251) and bounds[-1] == (1751, 2001)
        assert sum(hi - lo for lo, hi in bounds) == 2001
        # graph_degree must fit the SMALLEST shard
        with pytest.raises(RaftError):
            pcagra.build_merged(
                comms, cagra.IndexParams(intermediate_graph_degree=16,
                                         graph_degree=8, seed=0),
                mdata[:40])  # 5-row shards < graph_degree
