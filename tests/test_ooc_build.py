"""Out-of-core streamed build (ISSUE 19, tier-1 ``ooc`` marker).

Covers the chunked-corpus build path end to end: the PARITY CONTRACT
(an index built from a temp-file ``np.memmap`` through
``core.chunked.ChunkedReader`` is BIT-EQUAL to its in-core twin — same
PRNG trainset, same list ranks, same codes), the ``build_stream``
admission gates (host AND device budgets refuse whole-or-nothing
BEFORE the coarse trainer or any staged chunk spends a byte), the
``extend()`` full-materialization fix (large host batches auto-route
through the chunked path), the warm-build discipline (a second
streamed build compiles nothing), ``obs.mem.plan(streamed=True)``
accuracy against the measured ledger peak at 100k, and the stream
layer's composition seams (tiered mmap adoption, rebuild compaction
and sharded folds taking ``ooc_chunk_rows``).

Deterministic: seeded data, explicit ``seed=`` build params, ledger
assertions RELATIVE (baseline-subtracted) — the ledger is a process
singleton and other tests' live indexes legitimately appear in it.
"""

import dataclasses
import gc

import numpy as np
import pytest

from raft_tpu.core import Resources, chunked
from raft_tpu.obs import compile as obs_compile
from raft_tpu.obs import mem as obs_mem
from raft_tpu.obs import metrics
from raft_tpu.serve.errors import MemoryBudgetError

pytestmark = pytest.mark.ooc


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

def _index_arrays(ix):
    return {f.name: np.asarray(getattr(ix, f.name))
            for f in dataclasses.fields(ix)
            if hasattr(getattr(ix, f.name), "shape")}


def _assert_bit_equal(a, b, context=""):
    """Every array field of two index dataclasses identical — shape AND
    bytes. The streamed build's whole claim is that chunking changes
    WHERE rows pass through, never what lands in the index."""
    fa, fb = _index_arrays(a), _index_arrays(b)
    assert fa.keys() == fb.keys()
    bad = [k for k in fa
           if fa[k].shape != fb[k].shape or not np.array_equal(fa[k], fb[k])]
    assert not bad, f"fields diverged {context}: {bad}"


def _ooc_chunks_total(kind=None):
    snap = metrics.snapshot().get("raft_tpu_build_ooc_chunks_total")
    if snap is None:
        return 0
    return sum(s["value"] for s in snap["series"]
               if kind is None or s["labels"].get("kind") == kind)


def _dev_total():
    gc.collect()
    return obs_mem.totals()["device_bytes"]


def _staging_entries():
    return [r for r in obs_mem.breakdown()
            if r["component"] == "build/staging"]


# ---------------------------------------------------------------------------
# parity: memmap-streamed build bit-equal to the in-core twin
# ---------------------------------------------------------------------------

def test_memmap_parity_ivf_flat(rng, tmp_path):
    """ISSUE 19 acceptance: an IVF-Flat index built from a raw-binary
    ``np.memmap`` corpus in ~5 chunks is bit-equal to the in-core build
    of the same rows — every field, including the order-sensitive list
    layout. Also pins the ooc metrics family: per-chunk counters tick
    and the chunk-rows gauge reflects the reader."""
    import jax.numpy as jnp

    from raft_tpu.neighbors import ivf_flat

    n, d = 20_000, 32
    data = rng.standard_normal((n, d)).astype(np.float32)
    raw = tmp_path / "corpus.f32"
    data.tofile(raw)

    params = ivf_flat.IndexParams(n_lists=64, seed=3)
    incore = ivf_flat.build(params, jnp.asarray(data))

    before = _ooc_chunks_total(kind="ivf_flat")
    reader = chunked.ChunkedReader.from_file(
        raw, dtype=np.float32, shape=(n, d), chunk_rows=4096)
    assert reader.n_chunks == 5
    streamed = ivf_flat.build(params, reader)

    _assert_bit_equal(incore, streamed, "(ivf_flat memmap vs in-core)")
    assert _ooc_chunks_total(kind="ivf_flat") >= before + reader.n_chunks
    snap = metrics.snapshot()
    assert snap["raft_tpu_build_ooc_chunk_rows"]["series"], (
        "the chunk-rows gauge must be set by the streamed build")
    staged = sum(s["value"] for s in
                 snap["raft_tpu_build_ooc_staged_bytes_total"]["series"])
    assert staged > 0


def test_npy_memmap_parity_ivf_pq(rng, tmp_path):
    """The IVF-PQ leg of the parity contract, through the ``.npy``
    mmap door: coarse centers, OPQ rotation, codebooks, per-list codes
    and ids all bit-equal — the residual-encode pass is chunk-order
    independent by construction."""
    import jax.numpy as jnp

    from raft_tpu.neighbors import ivf_pq

    n, d = 20_000, 32
    data = rng.standard_normal((n, d)).astype(np.float32)
    path = tmp_path / "corpus.npy"
    np.save(path, data)

    params = ivf_pq.IndexParams(n_lists=64, pq_dim=8, seed=5)
    incore = ivf_pq.build(params, jnp.asarray(data))
    streamed = ivf_pq.build(
        params, chunked.ChunkedReader.from_file(path, chunk_rows=4096))
    _assert_bit_equal(incore, streamed, "(ivf_pq .npy vs in-core)")


def test_memmap_parity_brute_force_uint8(rng, tmp_path):
    """Dataset-resident kinds stream too: brute force materializes the
    reader chunk-by-chunk into ONE device array — bit-equal rows, and
    the s8-shift for uint8 corpora applied identically."""
    from raft_tpu.neighbors import brute_force

    n, d = 10_000, 16
    data = rng.integers(0, 256, (n, d), dtype=np.uint8)
    raw = tmp_path / "corpus.u8"
    data.tofile(raw)

    incore = brute_force.BruteForce().build(data)
    streamed = brute_force.BruteForce().build(
        chunked.ChunkedReader.from_file(raw, dtype=np.uint8, shape=(n, d),
                                        chunk_rows=3000))
    assert np.array_equal(np.asarray(incore.dataset),
                          np.asarray(streamed.dataset))


def test_memmap_parity_cagra(rng, tmp_path):
    """CAGRA parity (slow: the knn-graph self-search dominates): the
    streamed dataset materialization feeds the same graph pipeline, so
    dataset AND graph come back bit-equal."""
    import jax.numpy as jnp

    from raft_tpu.neighbors import cagra

    n, d = 4096, 16
    data = rng.standard_normal((n, d)).astype(np.float32)
    path = tmp_path / "corpus.npy"
    np.save(path, data)

    params = cagra.IndexParams(intermediate_graph_degree=16,
                               graph_degree=8)
    incore = cagra.build(params, jnp.asarray(data))
    streamed = cagra.build(
        params, chunked.ChunkedReader.from_file(path, chunk_rows=1000))
    assert np.array_equal(np.asarray(incore.dataset),
                          np.asarray(streamed.dataset))
    assert np.array_equal(np.asarray(incore.graph),
                          np.asarray(streamed.graph))


# ---------------------------------------------------------------------------
# admission gates: whole-or-nothing, before anything spends
# ---------------------------------------------------------------------------

def test_host_budget_refuses_before_any_chunk(rng):
    """ISSUE 19 satellite: an armed ``host_budget_bytes`` the staging +
    trainset peak exceeds refuses at ``site="build_stream/host"``
    BEFORE the coarse trainer or any staged chunk lands — ledger device
    bytes untouched, no staging entry, no chunk counter tick."""
    from raft_tpu.neighbors import ivf_flat, ivf_pq

    data = rng.standard_normal((4000, 16)).astype(np.float32)
    res = Resources(host_budget_bytes=1 << 10)
    for mod, params in ((ivf_flat, ivf_flat.IndexParams(n_lists=16)),
                        (ivf_pq, ivf_pq.IndexParams(n_lists=16, pq_dim=4))):
        dev0, chunks0 = _dev_total(), _ooc_chunks_total()
        staging0 = len(_staging_entries())
        with pytest.raises(MemoryBudgetError) as ei:
            mod.build(params, chunked.ChunkedReader(data, chunk_rows=1000),
                      res=res)
        assert ei.value.site == "build_stream/host", ei.value.site
        assert _dev_total() == dev0
        assert _ooc_chunks_total() == chunks0
        assert len(_staging_entries()) == staging0


def test_device_budget_refuses_streamed_build(rng):
    """The device half of the gate: the streamed build prices its peak
    (index + staged slots + labels) against ``memory_budget_bytes`` and
    refuses at ``site="build_stream"`` whole-or-nothing."""
    from raft_tpu.neighbors import ivf_flat

    data = rng.standard_normal((4000, 16)).astype(np.float32)
    res = Resources(memory_budget_bytes=1 << 10)
    dev0 = _dev_total()
    with pytest.raises(MemoryBudgetError) as ei:
        ivf_flat.build(ivf_flat.IndexParams(n_lists=16),
                       chunked.ChunkedReader(data, chunk_rows=1000), res=res)
    assert ei.value.site == "build_stream", ei.value.site
    assert _dev_total() == dev0


# ---------------------------------------------------------------------------
# extend(): the full-materialization fix
# ---------------------------------------------------------------------------

def test_extend_auto_wraps_large_host_batches(rng, monkeypatch):
    """The regression the fix exists for: a host ndarray batch past
    ``_STREAM_EXTEND_BYTES`` must take the chunked path (per-chunk
    assign + scatter — chunk counters tick) and still come back
    bit-equal to the in-core extend of a twin index. Patching the
    ivf_flat threshold covers ivf_pq too — its extend imports the same
    module global."""
    import jax.numpy as jnp

    from raft_tpu.neighbors import ivf_flat, ivf_pq

    n, d = 4000, 16
    data = rng.standard_normal((n, d)).astype(np.float32)
    batch = rng.standard_normal((1500, d)).astype(np.float32)
    monkeypatch.setattr(ivf_flat, "_STREAM_EXTEND_BYTES", 1 << 12)

    for mod, params in (
            (ivf_flat, ivf_flat.IndexParams(n_lists=32, seed=8)),
            (ivf_pq, ivf_pq.IndexParams(n_lists=32, pq_dim=8, seed=9))):
        kind = mod.__name__.rsplit(".", 1)[-1]
        base_a = mod.build(params, jnp.asarray(data))
        base_b = mod.build(params, jnp.asarray(data))
        # jnp input is not an ndarray -> stays on the in-core path
        incore = mod.extend(base_a, jnp.asarray(batch))
        before = _ooc_chunks_total(kind=kind)
        streamed = mod.extend(base_b, batch)
        assert _ooc_chunks_total(kind=kind) > before, (
            f"{kind}: the oversized host batch must stream")
        _assert_bit_equal(incore, streamed, f"({kind} auto-wrapped extend)")


def test_extend_small_batches_stay_in_core(rng):
    """Batches under the threshold keep the one-shot path — no chunk
    counter tick, no behavior change for the common small append."""
    from raft_tpu.neighbors import ivf_flat

    data = rng.standard_normal((3000, 16)).astype(np.float32)
    idx = ivf_flat.build(ivf_flat.IndexParams(n_lists=16, seed=1), data)
    before = _ooc_chunks_total(kind="ivf_flat")
    ivf_flat.extend(idx, rng.standard_normal((64, 16)).astype(np.float32))
    assert _ooc_chunks_total(kind="ivf_flat") == before


# ---------------------------------------------------------------------------
# warm-build discipline: the chunked loop must not sync or recompile
# ---------------------------------------------------------------------------

def test_second_streamed_build_compiles_nothing(rng):
    """ISSUE 19 satellite (dispatch-attribution guard): with shapes
    warm, a whole streamed ivf_pq rebuild — stage, assign, residual
    encode, scatter — launches ZERO fresh XLA programs. A per-chunk
    host round-trip or shape wobble would show up here first."""
    from raft_tpu.neighbors import ivf_pq

    data = rng.standard_normal((8000, 16)).astype(np.float32)
    params = ivf_pq.IndexParams(n_lists=32, pq_dim=4, seed=2)
    reader = chunked.ChunkedReader(data, chunk_rows=2000)
    first = ivf_pq.build(params, reader)
    with obs_compile.attribution() as rec:
        second = ivf_pq.build(params, reader)
    if not rec.available:
        pytest.skip("jax monitoring hooks unavailable")
    assert rec.programs == 0, (
        f"warm streamed rebuild compiled {rec.programs} programs "
        f"({rec.compile_s:.3f}s)")
    _assert_bit_equal(first, second, "(streamed rebuild determinism)")


# ---------------------------------------------------------------------------
# plan(streamed=True) accuracy
# ---------------------------------------------------------------------------

def test_plan_streamed_within_20pct_at_100k(rng):
    """ISSUE 19 satellite: the streamed-mode estimate vs the measured
    ledger peak of a REAL chunked build at 100k rows, same ±20%
    contract as the in-core estimator suite (test_obs_mem). plan()
    slightly overestimates by design — the labels scratch it prices is
    transient and partially outside the accounted window."""
    import jax

    from raft_tpu.neighbors import ivf_flat

    n, d, cr = 100_000, 16, 8192
    params = ivf_flat.IndexParams(n_lists=256, kmeans_n_iters=4)
    data = rng.random((n, d)).astype(np.float32)

    est = obs_mem.plan("ivf_flat", params, n, d, streamed=True,
                       chunk_rows=cr)
    assert est["host_peak_bytes"] > 0, "streamed plan must price host"

    baseline = _dev_total()
    obs_mem.reset_peak()
    idx = ivf_flat.build(params, chunked.ChunkedReader(data, chunk_rows=cr))
    jax.block_until_ready(jax.tree_util.tree_leaves(idx))
    measured = obs_mem.totals()["device_peak_bytes"] - baseline
    assert measured > 0
    assert abs(est["build_peak_bytes"] - measured) <= 0.20 * measured, (
        f"streamed plan {est['build_peak_bytes']} vs measured {measured} "
        f"({est['build_peak_bytes'] / measured:.3f}x) outside ±20%")


# ---------------------------------------------------------------------------
# stream-layer composition: tiered adoption, compaction, sharded folds
# ---------------------------------------------------------------------------

def test_tiered_store_adopts_mmap_corpus(rng, tmp_path):
    """A ``MutableIndex(dataset=reader, storage="tiered")`` over an
    mmap corpus ADOPTS the mapping as its cold tier in place: residency
    "disk", ZERO host bytes accounted (pages are disk-backed), and the
    refine hop serves straight off it."""
    import jax.numpy as jnp

    from raft_tpu import stream
    from raft_tpu.neighbors import ivf_pq

    n, d = 4000, 24
    data = rng.standard_normal((n, d)).astype(np.float32)
    path = tmp_path / "corpus.npy"
    np.save(path, data)

    reader = chunked.ChunkedReader.from_file(path, chunk_rows=900)
    params = ivf_pq.IndexParams(n_lists=16, seed=1)
    sealed = ivf_pq.build(params, reader)
    mi = stream.MutableIndex(sealed, dataset=reader, index_params=params,
                             storage="tiered", name="ooc_tiered_adopt")
    ts = mi.tiered_store
    assert ts.residency == "disk"
    tb = ts.tier_bytes()
    assert tb["host"] == 0 and tb["device"] == 0
    assert tb["disk"] == n * d * 4
    _, ids = mi.search_refined(jnp.asarray(data[:8]), 5, 4)
    assert np.asarray(ids).shape == (8, 5)


def test_compact_rebuild_takes_ooc_chunk_rows(rng):
    """Rebuild compaction through the chunked reader is bit-equal to
    the in-core fold: same live rows, same sealed result — the
    compactor only changes how rows travel to the builder."""
    from raft_tpu import stream
    from raft_tpu.neighbors import ivf_flat

    n, d = 2500, 16
    data = rng.standard_normal((n, d)).astype(np.float32)
    extra = rng.standard_normal((50, d)).astype(np.float32)
    params = ivf_flat.IndexParams(n_lists=16, seed=2)

    def make(name):
        m = stream.MutableIndex(ivf_flat.build(params, data),
                                dataset=data, index_params=params,
                                name=name)
        m.upsert(extra)
        m.delete(np.arange(10))
        return m

    m_incore, m_ooc = make("ooc_cmp_a"), make("ooc_cmp_b")
    m_incore.compact(mode="rebuild")
    m_ooc.compact(mode="rebuild", ooc_chunk_rows=777)
    _assert_bit_equal(m_incore._state.sealed, m_ooc._state.sealed,
                      "(rebuild compact via reader)")


def test_compact_ooc_chunk_rows_requires_rebuild(rng):
    """The knob is rebuild-only — extend-mode compaction never re-reads
    the corpus, so accepting the argument there would lie."""
    from raft_tpu import stream
    from raft_tpu.core.errors import RaftError
    from raft_tpu.neighbors import ivf_flat

    data = rng.standard_normal((2000, 16)).astype(np.float32)
    params = ivf_flat.IndexParams(n_lists=16, seed=4)
    m = stream.MutableIndex(ivf_flat.build(params, data), dataset=data,
                            index_params=params, name="ooc_mode_guard")
    with pytest.raises(RaftError):
        m.compact(mode="extend", ooc_chunk_rows=512)


def test_sharded_builds_from_reader_and_ooc_compacts(rng, tmp_path):
    """The mesh seam: a ShardedMutableIndex takes the reader directly
    (per-shard rows gathered via ``take`` — only the home shard's pages
    are touched), serves, and per-shard rebuild folds forward
    ``ooc_chunk_rows``."""
    import jax.numpy as jnp

    from raft_tpu import stream
    from raft_tpu.neighbors import ivf_flat

    n, d = 2500, 16
    data = rng.standard_normal((n, d)).astype(np.float32)
    path = tmp_path / "corpus.npy"
    np.save(path, data)
    params = ivf_flat.IndexParams(n_lists=16, seed=6)

    sm = stream.ShardedMutableIndex(
        chunked.ChunkedReader.from_file(path, chunk_rows=900),
        n_shards=2, build=lambda rows: ivf_flat.build(params, rows),
        index_params=params)
    _, ids = sm.search(jnp.asarray(data[:4]), 5)
    assert np.asarray(ids).shape == (4, 5)
    rep = sm.compact(mode="rebuild", shard=0, ooc_chunk_rows=512)
    assert rep["mode"] == "rebuild" and rep["shard"] == 0


# ---------------------------------------------------------------------------
# 10M-class (slow manifest)
# ---------------------------------------------------------------------------

def test_ooc_build_10m_class(rng, tmp_path):
    """The scale the subsystem exists for (slow manifest): a 10M-row
    uint8 corpus — 320 MB, deliberately bigger than any single staged
    allocation by orders of magnitude — streamed off disk. The measured
    device peak must stay INSIDE the streamed plan's +20% admission
    envelope (whose staging term is two chunks — corpus size shows up
    as index bytes, never as a whole-corpus staging copy; the plan's
    transient label scratch sits partly outside the accounted window,
    so the bound is one-sided at this scale), and the result must
    serve."""
    import jax

    from raft_tpu.neighbors import ivf_flat

    n, d, cr = 10_000_000, 32, 262_144
    raw = tmp_path / "corpus10m.u8"
    mm = np.memmap(raw, dtype=np.uint8, mode="w+", shape=(n, d))
    chunk = 1_000_000
    for s in range(0, n, chunk):
        e = min(s + chunk, n)
        mm[s:e] = rng.integers(0, 256, (e - s, d), dtype=np.uint8)
    mm.flush()
    del mm

    params = ivf_flat.IndexParams(n_lists=1024, kmeans_n_iters=4,
                                  kmeans_trainset_fraction=0.02, seed=0)
    reader = chunked.ChunkedReader.from_file(raw, dtype=np.uint8,
                                             shape=(n, d), chunk_rows=cr)
    est = obs_mem.plan("ivf_flat", params, n, d, dtype="uint8",
                       streamed=True, chunk_rows=cr)
    baseline = _dev_total()
    obs_mem.reset_peak()
    idx = ivf_flat.build(params, reader)
    jax.block_until_ready(jax.tree_util.tree_leaves(idx))
    measured = obs_mem.totals()["device_peak_bytes"] - baseline
    assert 0 < measured <= 1.2 * est["build_peak_bytes"], (
        f"10M streamed peak {measured} above plan "
        f"{est['build_peak_bytes']} +20%")

    q = rng.integers(0, 256, (4, d), dtype=np.uint8)
    _, ids = ivf_flat.search(ivf_flat.SearchParams(n_probes=8), idx, q, 10)
    assert np.asarray(ids).shape == (4, 10)
