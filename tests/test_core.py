"""Core layer tests (reference analogue: cpp/test/core/*.cu, CORE_TEST)."""

import io

import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu.core import (
    RaftError,
    Resources,
    default_resources,
    deserialize_mdspan,
    deserialize_scalar,
    expects,
    fail,
    serialize_mdspan,
    serialize_scalar,
)


class TestErrors:
    def test_expects_pass(self):
        expects(True, "should not raise")

    def test_expects_fail(self):
        with pytest.raises(RaftError, match="n must be 3"):
            expects(False, "n must be %d", 3)

    def test_fail(self):
        with pytest.raises(RaftError):
            fail("boom")


class TestResources:
    def test_default_singleton(self):
        assert default_resources() is default_resources()

    def test_registry(self):
        r = Resources()
        assert not r.has_resource("x")
        r.set_resource("x", 42)
        assert r.get_resource("x") == 42

    def test_comms_uninitialized(self):
        r = Resources()
        assert not r.comms_initialized
        with pytest.raises(RaftError):
            r.get_comms()

    def test_put_and_sync(self):
        r = Resources()
        x = r.put(np.arange(8, dtype=np.float32))
        r.sync(x)
        np.testing.assert_array_equal(np.asarray(x), np.arange(8))

    def test_device_count_no_mesh(self):
        assert Resources().device_count == 1


class TestSerialize:
    def test_mdspan_roundtrip(self):
        buf = io.BytesIO()
        a = jnp.arange(12, dtype=jnp.float32).reshape(3, 4)
        serialize_mdspan(buf, a)
        buf.seek(0)
        b = deserialize_mdspan(buf)
        np.testing.assert_array_equal(b, np.asarray(a))
        assert b.dtype == np.float32

    def test_scalar_roundtrip(self):
        buf = io.BytesIO()
        for v in [7, 3.5, True, False, "ivf_pq"]:
            serialize_scalar(buf, v)
        buf.seek(0)
        assert deserialize_scalar(buf) == 7
        assert deserialize_scalar(buf) == 3.5
        assert deserialize_scalar(buf) is True
        assert deserialize_scalar(buf) is False
        assert deserialize_scalar(buf) == "ivf_pq"

    def test_mixed_stream(self):
        # index-file layout: scalars then array blocks (ivf_pq_serialize.cuh pattern)
        buf = io.BytesIO()
        serialize_scalar(buf, 2)
        serialize_mdspan(buf, jnp.ones((2, 2)))
        serialize_mdspan(buf, jnp.zeros((1, 3)))
        buf.seek(0)
        assert deserialize_scalar(buf) == 2
        np.testing.assert_array_equal(deserialize_mdspan(buf), np.ones((2, 2)))
        np.testing.assert_array_equal(deserialize_mdspan(buf), np.zeros((1, 3)))


def test_mesh_fixture(mesh8):
    assert mesh8.size == 8


def test_operators_vocabulary(rng):
    """Reference operators.hpp parity: functors compose and KVP reductions
    pick the right element."""
    import jax.numpy as jnp
    from raft_tpu.core import operators as ops

    x = jnp.asarray(rng.random(16).astype(np.float32))
    np.testing.assert_allclose(np.asarray(ops.sq_op(x)), np.asarray(x) ** 2, rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(ops.div_checkzero_op(x, jnp.zeros_like(x))), 0.0
    )
    f = ops.compose_op(ops.sqrt_op, ops.sq_op)
    np.testing.assert_allclose(np.asarray(f(x)), np.abs(np.asarray(x)), rtol=1e-6)
    add3 = ops.plug_const_op(3.0, ops.add_op)
    np.testing.assert_allclose(np.asarray(add3(x)), np.asarray(x) + 3.0, rtol=1e-6)

    a = ops.KeyValuePair(jnp.int32(1), jnp.float32(0.5))
    b = ops.KeyValuePair(jnp.int32(2), jnp.float32(0.25))
    r = ops.argmin_op(a, b)
    assert int(r.key) == 2 and float(r.value) == 0.25
    r = ops.argmax_op(a, b)
    assert int(r.key) == 1 and float(r.value) == 0.5


class TestSerializationVersion:
    """Format-version header (reference: serialization_version checks,
    ivf_flat_serialize.cuh:37,135)."""

    def test_old_unversioned_stream_fails_clearly(self, tmp_path):
        # a pre-versioning stream: tag followed directly by the metric int
        from raft_tpu.core import RaftError, serialize_scalar
        from raft_tpu.neighbors import ivf_flat

        path = str(tmp_path / "old.bin")
        with open(path, "wb") as f:
            serialize_scalar(f, "ivf_flat")
            serialize_scalar(f, 1)          # old layout: metric enum here
        with pytest.raises(RaftError, match="unsupported ivf_flat index file format"):
            ivf_flat.load(path)

    def test_version_roundtrip_all_indexes(self, tmp_path, rng):
        import jax.numpy as jnp
        from raft_tpu.neighbors import cagra, ivf_flat, ivf_pq

        x = jnp.asarray(rng.random((256, 16), "float32"))
        idx = ivf_flat.build(ivf_flat.IndexParams(n_lists=8, seed=0), x)
        p = str(tmp_path / "a.bin")
        ivf_flat.save(idx, p)
        assert ivf_flat.load(p).metric == idx.metric

        pq = ivf_pq.build(ivf_pq.IndexParams(n_lists=8, pq_dim=8, seed=0), x)
        p2 = str(tmp_path / "b.bin")
        ivf_pq.save(pq, p2)
        assert ivf_pq.load(p2).pq_bits == pq.pq_bits

    def test_unchanged_formats_read_previous_version(self, tmp_path, rng):
        """Old-layout files must keep loading where the layout is compatible
        (no collateral rebuilds when the global version bumps): ivf_flat
        streams in the /3-era layout (no data_kind scalar — what both /3 and
        /4 headers wrote; the /4 bump was cagra's) and an ivf_pq /3 file
        (layout unchanged since) all load; an ivf_pq raft_tpu/2 header must
        fail."""
        import jax.numpy as jnp
        from raft_tpu.core import RaftError
        from raft_tpu.core.serialize import (serialize_mdspan, serialize_scalar)
        from raft_tpu.neighbors import ivf_flat, ivf_pq

        x = jnp.asarray(rng.random((256, 16), "float32"))
        idx = ivf_flat.build(ivf_flat.IndexParams(n_lists=8, seed=0), x)
        # hand-write the pre-/5 ivf_flat layout: header, metric,
        # split_factor, then the five mdspans — no data_kind scalar. The /4
        # case is the REAL-WORLD one: every ivf_flat file saved between the
        # /4 and /5 bumps has exactly this shape.
        for old_ver in ("raft_tpu/3", "raft_tpu/4"):
            p = str(tmp_path / f"{old_ver.replace('/', '_')}.bin")
            with open(p, "wb") as f:
                serialize_scalar(f, "ivf_flat")
                serialize_scalar(f, old_ver)
                serialize_scalar(f, int(idx.metric))
                serialize_scalar(f, float(idx.split_factor))
                for arr in (idx.centers, idx.list_data, idx.list_ids,
                            idx.list_norms, idx.list_sizes):
                    serialize_mdspan(f, arr)
            loaded = ivf_flat.load(p)
            assert loaded.metric == idx.metric
            assert loaded.data_kind == "float32", old_ver  # from stored dtype

        pq = ivf_pq.build(ivf_pq.IndexParams(n_lists=8, pq_dim=8, seed=0), x)
        # hand-write the true /3-era ivf_pq layout (the splice-a-current-file
        # approach rotted at the /6 bump: the current writer emits data_kind
        # + list_scales, which an old header tells the reader to skip):
        # header, metric, codebook_kind, pq_bits, split_factor, pq_split,
        # then exactly 8 mdspans — no data_kind scalar, no list_scales.
        for old_ver in ("raft_tpu/3", "raft_tpu/4", "raft_tpu/5"):
            p2 = str(tmp_path / f"pq_{old_ver.replace('/', '_')}.bin")
            with open(p2, "wb") as f:
                serialize_scalar(f, "ivf_pq")
                serialize_scalar(f, old_ver)
                serialize_scalar(f, int(pq.metric))
                serialize_scalar(f, pq.codebook_kind)
                serialize_scalar(f, pq.pq_bits)
                serialize_scalar(f, float(pq.split_factor))
                serialize_scalar(f, bool(pq.pq_split))
                for arr in (pq.centers, pq.centers_rot, pq.rotation,
                            pq.codebooks, pq.list_codes, pq.list_ids,
                            pq.list_sizes, pq.list_consts):
                    serialize_mdspan(f, arr)
            loaded = ivf_pq.load(p2)
            assert loaded.pq_bits == pq.pq_bits
            assert loaded.data_kind == "float32"  # pre-/6 files are float
            assert loaded.list_scales.shape == (0,)  # pre-/7: norm disabled
        # /2 ivf_pq layout predates pq_split/list_consts: must fail clearly
        p3 = str(tmp_path / "pq_v2.bin")
        with open(p3, "wb") as f:
            serialize_scalar(f, "ivf_pq")
            serialize_scalar(f, "raft_tpu/2")
        with pytest.raises(RaftError, match="unsupported ivf_pq index file format"):
            ivf_pq.load(p3)


def test_output_conversion_skips_tracers(rng):
    """@auto_convert_output entry points called inside a user's jit must pass
    tracers through untouched (the eager outermost call converts); with
    set_output_as('numpy') a traced conversion would raise."""
    import jax
    import jax.numpy as jnp

    import raft_tpu.config as config
    from raft_tpu.matrix import select_k

    x = jnp.asarray(rng.random((4, 32), "float32"))
    config.set_output_as("numpy")
    try:
        v, i = jax.jit(lambda a: select_k(a, 3))(x)   # traced call: no convert
        assert isinstance(v, jax.Array)
        v2, i2 = select_k(x, 3)                        # eager call: converts
        assert isinstance(v2, np.ndarray)
    finally:
        config.set_output_as("jax")
