"""Pipelined-flush tests (tier-1 ``serve`` marker, ISSUE 12).

The host-free flush pipeline's contracts, all deterministic (injected
clocks, ``start_workers=False`` + ``pump(complete=False)`` /
``complete()`` to drive the completion stage by hand — no wall sleeps):

- pipelined results are identical to the synchronous flush path;
- OUT-OF-ORDER completion: a slow flush N finishing after N+1's device
  work resolves only its own futures, with per-batch request-log and SLO
  attribution intact;
- an in-flight flush that raises AFTER the handoff fails exactly its
  batch (and a dispatch-time raise releases the registry lease);
- the in-flight window is bounded by ``pipeline_depth``;
- staging buffers are ledger-accounted and FLAT across flushes, with
  donation actually freeing the previous query buffer in pinned mode;
- the warm ladder covers the staging programs: zero cold compiles across
  pipelined flushes after publish;
- the fused scatter-gather gather skips merge-device-resident parts.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from raft_tpu import obs, stream
from raft_tpu.neighbors import brute_force
from raft_tpu.obs import dispatch as obs_dispatch
from raft_tpu.obs import mem as obs_mem
from raft_tpu.obs import requestlog
from raft_tpu.serve import (MicroBatcher, PendingFlush, SearchService,
                            StagingBuffers, warm_staging)

pytestmark = pytest.mark.serve


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


class SLORecorder:
    """Minimal SLO stand-in: records (queue_wait, flush) samples."""

    def __init__(self):
        self.requests = []

    def record_request(self, wait, flush):
        self.requests.append((wait, flush))

    def record_admission(self, ok):
        pass


@pytest.fixture
def dataset(rng):
    return rng.standard_normal((256, 16)).astype(np.float32)


@pytest.fixture
def bf(dataset):
    return brute_force.BruteForce().build(dataset)


def det_service(bf_index, clock, *, depth=2, warm=False, **kw):
    svc = SearchService(max_batch=8, max_wait_us=1000.0, max_queue_rows=64,
                        clock=clock, start_workers=False,
                        pipeline_depth=depth, **kw)
    svc.publish("main", bf_index, k=5, warm=warm)
    return svc


# -- parity with the synchronous path ----------------------------------------

def test_pipelined_results_match_sync(bf, dataset):
    blocks = [dataset[0:3], dataset[3:4], dataset[4:9], dataset[9:11]]
    outs = {}
    for depth in (0, 2):
        clock = FakeClock()
        svc = det_service(bf, clock, depth=depth)
        futs = [svc.submit("main", b, 5) for b in blocks]
        clock.advance(0.01)
        while svc.pump(force=True):
            pass
        outs[depth] = [f.result(timeout=0) for f in futs]
        svc.shutdown()
    for (d0, i0), (d2, i2) in zip(outs[0], outs[2]):
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i2))
        np.testing.assert_allclose(np.asarray(d0), np.asarray(d2), rtol=1e-6)


def test_pump_complete_false_defers_resolution(bf, dataset):
    clock = FakeClock()
    svc = det_service(bf, clock, depth=2)
    fut = svc.submit("main", dataset[:2], 5)
    clock.advance(0.01)
    b = svc._batchers[("main", 5)]
    assert b.pump(complete=False) == 2
    assert not fut.done() and b.inflight() == 1
    assert b.complete() == 1
    assert fut.result(timeout=0)[0].shape == (2, 5)
    svc.shutdown()


# -- out-of-order completion ---------------------------------------------------

def _pending_flush_fn(clock, plans):
    """A flush_fn yielding PendingFlush objects per flush, in order:
    each plan is (materialize_delay, exception_or_None). The device
    'result' echoes row ids so the scatter is checkable."""
    state = {"i": 0}

    def flush(q):
        n = state["i"] = state["i"] + 1
        delay, exc = plans[n - 1]
        rows = np.asarray(q)

        def materialize():
            clock.advance(delay)  # a slow device-side materialization
            if exc is not None:
                raise exc
            ids = np.arange(rows.shape[0])[:, None] * np.ones((1, 3))
            return (np.full((rows.shape[0], 3), n, np.float32),
                    ids.astype(np.int32))

        return PendingFlush(materialize, dispatches=7)

    return flush


def test_slow_flush_resolves_only_its_own_futures(dataset):
    """Flush A materializes SLOWLY after flush B was already dispatched:
    A's completion resolves exactly A's futures with A's results, B's
    resolve separately, and each batch keeps its own queue/flush spans in
    the request log and its own SLO sample (per-batch attribution
    survives the handoff)."""
    clock = FakeClock()
    log = requestlog.RequestLog(clock=clock)
    slo = SLORecorder()
    b = MicroBatcher(_pending_flush_fn(clock, [(5.0, None), (0.5, None)]),
                     max_batch=4, max_wait_us=0.0, clock=clock, start=False,
                     pipeline_depth=2, request_log=log, slo=slo)
    fa = b.submit(dataset[:2], rid=log.begin("s", 2))
    assert b.pump(complete=False) == 2          # A dispatched at t=0
    clock.advance(1.0)
    fb = b.submit(dataset[2:3], rid=log.begin("s", 1))
    assert b.pump(complete=False) == 1          # B dispatched at t=1
    assert b.inflight() == 2
    assert not fa.done() and not fb.done()

    assert b.complete(1) == 1                   # A materializes (t=1 -> 6)
    assert fa.done() and not fb.done()
    da, ia = fa.result(timeout=0)
    assert da.shape == (2, 3) and float(da[0, 0]) == 1.0  # flush #1's data
    assert b.complete(1) == 1                   # B materializes (t=6 -> 6.5)
    db, _ = fb.result(timeout=0)
    assert db.shape == (1, 3) and float(db[0, 0]) == 2.0  # flush #2's data

    entries = {e["rid"]: e for e in log.recent()}
    assert len(entries) == 2
    (ra, rb) = sorted(entries)                  # req-00000001, req-00000002
    # A: queued 0s, dispatched at 0, materialized at 6 -> flush span 6.0
    assert entries[ra]["spans_ms"]["queue"] == pytest.approx(0.0)
    assert entries[ra]["spans_ms"]["flush"] == pytest.approx(6000.0)
    # B: dispatched at 1, completed at 6.5 -> flush span 5.5 (includes the
    # documented completion-stage wait behind slow A), queue 0
    assert entries[rb]["spans_ms"]["queue"] == pytest.approx(0.0)
    assert entries[rb]["spans_ms"]["flush"] == pytest.approx(5500.0)
    assert [o["outcome"] for o in entries.values()] == ["ok", "ok"]
    # SLO saw one sample per request with the same per-batch split
    assert sorted(f for _, f in slo.requests) == pytest.approx([5.5, 6.0])
    b.close()


def test_inflight_raise_after_handoff_fails_exactly_its_batch(dataset):
    clock = FakeClock()
    log = requestlog.RequestLog(clock=clock)
    before = obs.to_json()
    boom = RuntimeError("materialize exploded")
    b = MicroBatcher(_pending_flush_fn(clock, [(0.0, boom), (0.0, None)]),
                     max_batch=4, max_wait_us=0.0, clock=clock, start=False,
                     pipeline_depth=2, request_log=log, stream="oops")
    fa = b.submit(dataset[:2], rid=log.begin("oops", 2))
    b.pump(complete=False)
    fb = b.submit(dataset[2:3], rid=log.begin("oops", 1))
    b.pump(complete=False)
    assert b.complete() == 2
    with pytest.raises(RuntimeError, match="materialize exploded"):
        fa.result(timeout=0)
    assert fb.result(timeout=0)[0].shape == (1, 3)  # B survived A's failure
    d = obs.delta(before, obs.to_json())
    assert d.get('raft_tpu_serve_flush_errors_total{stream="oops"}') == 1
    outcomes = {e["rid"]: e["outcome"] for e in log.recent()}
    assert sorted(outcomes.values()) == ["error", "ok"]
    b.close()


def test_dispatch_raise_fails_batch_and_releases_lease(bf, dataset):
    """A flush that raises AT DISPATCH (before the handoff) fails its
    batch and must not strand the registry lease — the raising version
    still retires after a republish."""
    calls = {"n": 0}

    def flaky(queries, k):
        calls["n"] += 1
        if calls["n"] == 1:
            raise ValueError("dispatch exploded")
        return bf.search(jnp.asarray(queries), k)

    flaky.kind, flaky.dim, flaky.query_dtype = "custom", 16, "float32"
    clock = FakeClock()
    svc = SearchService(max_batch=8, clock=clock, start_workers=False,
                        pipeline_depth=2)
    svc.publish("main", flaky, k=5, warm=False)
    fut = svc.submit("main", dataset[:1], 5)
    clock.advance(0.01)
    svc.pump()
    with pytest.raises(ValueError, match="dispatch exploded"):
        fut.result(timeout=0)
    svc.publish("main", bf, k=5, warm=False)  # flips; v1 must be retirable
    assert svc.registry.live_versions("main") == (2,)
    fut = svc.submit("main", dataset[:1], 5)
    clock.advance(0.01)
    svc.pump()
    assert fut.result(timeout=0)[0].shape == (1, 5)
    svc.shutdown()


# -- the bounded window --------------------------------------------------------

def test_inflight_window_bounded_by_depth(dataset):
    clock = FakeClock()
    plans = [(0.0, None)] * 4
    b = MicroBatcher(_pending_flush_fn(clock, plans), max_batch=4,
                     max_wait_us=0.0, clock=clock, start=False,
                     pipeline_depth=2)
    futs = []
    for j in range(3):
        futs.append(b.submit(dataset[j:j + 1]))
        b.pump(complete=False)
    # the third handoff completed the OLDEST inline to hold the bound
    assert b.inflight() == 2
    assert futs[0].done() and not futs[2].done()
    b.complete()
    assert all(f.done() for f in futs)
    b.close()


def test_drain_shutdown_with_backlog_under_live_workers(bf, dataset):
    """shutdown(drain=True) with a queued backlog, live workers and pinned
    staging: the in-flight bound must hold through the close window with
    the completion worker outliving the flush worker's final drain. (The
    failure mode: the completer exiting on a momentarily-empty stage
    stranded the flush worker on the bound, close()'s join timed out, and
    its drain pump flushed CONCURRENTLY with the revived worker —
    double-donating a staging slot, 'buffer has been deleted or donated'
    failures.)"""
    svc = SearchService(max_batch=8, max_wait_us=100000.0, pipeline_depth=2,
                        staging_device=jax.devices()[0])
    svc.publish("main", bf, k=5, warm=True)
    # max_wait 100ms: the backlog is still queued when shutdown starts
    futs = [svc.submit("main", dataset[j:j + 1], 5) for j in range(64)]
    svc.shutdown(drain=True, timeout_s=30)
    ref_i = np.asarray(bf.search(jnp.asarray(dataset[:64]), 5)[1])
    for j, f in enumerate(futs):
        d, i = f.result(timeout=0)  # resolved by the drain, not by us
        np.testing.assert_array_equal(np.asarray(i)[0], ref_i[j])


def test_close_drains_inflight(bf, dataset):
    clock = FakeClock()
    svc = det_service(bf, clock, depth=2)
    fut = svc.submit("main", dataset[:2], 5)
    clock.advance(0.01)
    svc._batchers[("main", 5)].pump(complete=False)
    assert not fut.done()
    svc.shutdown(drain=True)  # close() drains the completion stage
    assert fut.result(timeout=0)[0].shape == (2, 5)


# -- staging ------------------------------------------------------------------

def test_staging_ledger_flat_and_donation_frees():
    dev = jax.devices()[0]
    st = StagingBuffers((1, 2, 4), 8, "float32", depth=2, device=dev,
                        stream="stg")
    rows = np.ones((3, 8), np.float32)
    levels = []
    old_slots = []
    for _ in range(5):
        host, dv = st.stage([rows], 3, 4)
        assert host.shape == (4, 8) and np.all(host[3] == 0)  # pad zeroed
        old_slots.append(dv)
        ent = [e for e in obs_mem.breakdown()
               if e["component"] == "serve/staging" and e["name"] == "stg"]
        assert len(ent) == 1
        levels.append((ent[0]["device_bytes"], ent[0]["host_bytes"]))
    # accounted staging bytes are FLAT across flushes — donation (or the
    # reference drop) returns the previous buffer's bytes every cycle
    assert len(set(levels)) == 1, levels
    s = st.stats()
    assert s["uploads"] == 5 and s["pinned"]
    # pinned mode: the donated previous slot is actually freed
    assert s["donation_frees"] >= 3, s
    assert old_slots[0].is_deleted() and old_slots[1].is_deleted()
    st.release()
    assert not any(e["component"] == "serve/staging" and e["name"] == "stg"
                   for e in obs_mem.breakdown())


def test_staging_unpinned_composes_with_sharded_mesh(rng):
    """Unpinned staging uploads are UNCOMMITTED, so a pipelined service can
    front a device-pinned sharded mesh (committed per-shard arrays) without
    a placement conflict — and results match the direct search."""
    data = rng.standard_normal((96, 12)).astype(np.float32)
    sm = stream.ShardedMutableIndex(
        data, n_shards=2,
        build=lambda x: brute_force.BruteForce().build(jnp.asarray(x)),
        devices=jax.devices()[:2], delta_capacity=16)
    clock = FakeClock()
    svc = SearchService(max_batch=4, clock=clock, start_workers=False,
                        pipeline_depth=2)
    svc.publish("mesh", sm, k=5, warm=False)
    q = data[:3]
    fut = svc.submit("mesh", q, 5)
    clock.advance(0.01)
    svc.pump()
    d, i = fut.result(timeout=0)
    dd, ii = sm.search(q, 5)
    np.testing.assert_array_equal(np.asarray(i), np.asarray(ii))
    np.testing.assert_allclose(np.asarray(d), np.asarray(dd), rtol=1e-6)
    svc.shutdown()


def test_staging_buffer_rotation_covers_inflight_window(dataset):
    """Flush N's host view must stay intact until N completes even while
    N+1 and N+2 assemble — the depth+2 rotation contract the canary tap
    relies on."""
    clock = FakeClock()
    seen = []

    def flush(q):
        rows = np.asarray(q)
        return PendingFlush(lambda r=rows: (r.copy(), r[:, :1].copy()))

    st = StagingBuffers((1, 2, 4), 16, "float32", depth=2, stream="rot")
    b = MicroBatcher(flush, max_batch=4, max_wait_us=0.0, clock=clock,
                     start=False, pipeline_depth=2, staging=st,
                     on_result=lambda q, out: seen.append(np.asarray(q).copy()))
    blocks = [dataset[j * 2:(j + 1) * 2] for j in range(3)]
    for blk in blocks:
        b.submit(blk)
        b.pump(complete=False)
    b.complete()
    assert len(seen) == 3
    for blk, got in zip(blocks, seen):
        np.testing.assert_array_equal(got, blk)  # no buffer was clobbered
    b.close()


def test_staging_rotation_survives_live_completion_worker(dataset):
    """The completion worker POPS an entry from the bounded stage before
    materializing it, which unblocks the flush worker one flush early —
    and staging happens BEFORE the handoff blocks, so the next batch is
    written while the popped flush's host view is still pending its
    canary tap. The depth+2 rotation must cover that window (depth+1 did
    not: the canary saw flush N+depth+1's queries against flush N's
    results)."""
    import threading as _threading
    import time as _time

    gate = _threading.Event()
    release = _threading.Event()
    seen = []

    def flush(q):
        rows = np.asarray(q)

        def materialize(r=rows):
            gate.set()  # popped off the stage; now wedge until released
            release.wait(10)
            return (r.copy(), r[:, :1].copy())

        return PendingFlush(materialize)

    st = StagingBuffers((1, 2), 16, "float32", depth=1, stream="live")
    # max_batch=2 and 2-row blocks: every submit is exactly one full
    # flush, so block j always lands in staging buffer j % n_host
    b = MicroBatcher(flush, max_batch=2, max_wait_us=0.0,
                     clock=_time.monotonic, start=True, pipeline_depth=1,
                     staging=st,
                     on_result=lambda q, out: seen.append(
                         np.asarray(q).copy()))
    blocks = [dataset[j * 2:(j + 1) * 2] for j in range(3)]
    futs = [b.submit(blocks[0])]
    assert gate.wait(10)  # flush 0 popped and wedged in materialize
    # flush 1 fills the depth-1 stage; flush 2 is STAGED before its
    # handoff blocks — the overwrite window for flush 0's buffer
    futs.append(b.submit(blocks[1]))
    futs.append(b.submit(blocks[2]))
    release.set()
    for f in futs:
        f.result(timeout=10)
    b.close()
    assert len(seen) == 3
    for blk, got in zip(blocks, seen):
        np.testing.assert_array_equal(got, blk)  # no buffer was clobbered


# -- warm coverage ------------------------------------------------------------

def test_pipelined_flushes_zero_cold_compiles_after_publish(bf, dataset):
    from raft_tpu.obs import compile as obs_compile

    clock = FakeClock()
    svc = SearchService(max_batch=4, clock=clock, start_workers=False,
                        pipeline_depth=2, staging_device=jax.devices()[0])
    report = svc.publish("main", bf, k=5, warm=True)
    assert report["staging_warmed"] == 3  # buckets 1, 2, 4
    with obs_compile.attribution() as rec:
        for j in range(4):
            fut = svc.submit("main", dataset[j:j + 2], 5)
            clock.advance(0.01)
            svc.pump()
            assert fut.result(timeout=0)[0].shape == (2, 5)
    assert rec.cache_misses == 0, "pipelined flush cold-compiled"
    assert rec.compile_s == 0.0
    svc.shutdown()


def test_staging_warm_runs_before_the_flip(bf, dataset):
    """A hot-swap republish must compile the pipelined flush path's
    committed-placement executables BEFORE the flip: serving traffic
    takes no publish lock, so warming them after publish() returns opens
    a cold window where a flush leases the new version first. The new
    searcher's staged warm calls must all observe the OLD version still
    active."""
    clock = FakeClock()
    svc = SearchService(max_batch=4, clock=clock, start_workers=False,
                        pipeline_depth=2, staging_device=jax.devices()[0])
    svc.publish("main", bf, k=5, warm=True)
    active_at_warm = []

    def hook(queries, k):
        active_at_warm.append(svc.registry.active("main").version)
        return bf.search(jnp.asarray(queries), k)

    hook.kind, hook.dim, hook.query_dtype = "custom", 16, "float32"
    report = svc.publish("main", hook, k=5, warm=True)
    assert report["staging_warmed"] == 3  # buckets 1, 2, 4
    assert active_at_warm and all(v == 1 for v in active_at_warm), \
        active_at_warm
    assert svc.registry.active("main").version == 2
    svc.shutdown()


# -- dispatch metering ---------------------------------------------------------

def test_dispatches_per_flush_recorded(bf, dataset):
    clock = FakeClock()
    before = obs.to_json()
    svc = det_service(bf, clock, depth=2)
    fut = svc.submit("main", dataset[:1], 5)
    clock.advance(0.01)
    svc.pump()
    fut.result(timeout=0)
    d = obs.delta(before, obs.to_json())
    # a plain sealed searcher counts as one dispatch site, plus the
    # staging upload the batcher meters at drain time
    assert d.get('raft_tpu_serve_dispatches_per_flush_count'
                 '{stream="main.k5"}') == 1
    assert d.get('raft_tpu_serve_dispatches_per_flush_sum'
                 '{stream="main.k5"}') == 2
    svc.shutdown()


def test_fused_gather_skips_resident_parts(rng):
    """S=2 device-pinned mesh: shard 0's candidate parts are already on
    the merge device, so the fused gather moves exactly shard 1's 4 arrays
    (2 parts x d+i) instead of all 8 — and the count is attributable via
    the dispatch meter and the stream_moved_parts trace note."""
    data = rng.standard_normal((96, 12)).astype(np.float32)
    sm = stream.ShardedMutableIndex(
        data, n_shards=2,
        build=lambda x: brute_force.BruteForce().build(jnp.asarray(x)),
        devices=jax.devices()[:2], delta_capacity=16)
    q = data[:3]
    sm.search(q, 5)  # warm the programs so counts are steady-state
    with requestlog.collect() as col:
        with obs_dispatch.count() as dc:
            sm.search(q, 5)
    assert col.notes["stream_moved_parts"] == 4, col.notes
    # scans (4 sites x 2 shards) + gather moves (4) + merge (1); no pads
    # at k=5 vs an 8-row delta bucket and 40+ sealed rows per shard
    assert dc.total == 13, dc.total

    # unpinned mesh: no merge device, nothing moves
    sm1 = stream.ShardedMutableIndex(
        data, n_shards=2,
        build=lambda x: brute_force.BruteForce().build(jnp.asarray(x)),
        delta_capacity=16)
    sm1.search(q, 5)
    with requestlog.collect() as col1:
        sm1.search(q, 5)
    assert col1.notes["stream_moved_parts"] == 0


# -- worker-thread end to end --------------------------------------------------

def test_pipelined_worker_threads_end_to_end(bf, dataset):
    svc = SearchService(max_batch=8, max_wait_us=200.0, pipeline_depth=2)
    svc.publish("main", bf, k=5, warm=False)
    futs = [svc.submit("main", dataset[j:j + 1], 5) for j in range(24)]
    ref_d, ref_i = bf.search(jnp.asarray(dataset[:24]), 5)
    for j, f in enumerate(futs):
        d, i = f.result(timeout=30)
        np.testing.assert_array_equal(np.asarray(i)[0], np.asarray(ref_i)[j])
    svc.shutdown()
