"""Autotuner tests (tier-1 ``tune`` marker): keying, sweep engine, decision
log, serialize /9 round trip + /8 back-compat, threshold pinning, and the
TUNE_r08.json drift pin (ISSUE 7).

The drift pin follows the calibrated-seed-pool template: the committed
artifact's recall numbers were measured on this exact mesh with seeded
generators, so rebuilding a family and re-measuring an operating point
must land within tolerance — QPS is never asserted (wall clock on shared
CI is noise); the matches-or-beats acceptance property is asserted from
the artifact's own numbers, which the choice rule guarantees by
construction and this suite keeps honest."""

import json
import pathlib

import numpy as np
import pytest

from raft_tpu import tune
from raft_tpu.core import serialize
from raft_tpu.core.errors import RaftError
from raft_tpu.neighbors import brute_force, cagra, ivf_flat, ivf_pq
from raft_tpu.tune import reference
from raft_tpu.tune.apply import search_fn
from raft_tpu.tune.sweep import _ground_truth, _recall

pytestmark = pytest.mark.tune

ARTIFACT = pathlib.Path(__file__).resolve().parents[1] / "TUNE_r08.json"


@pytest.fixture(scope="module")
def small():
    """One small ivf_flat family shared by the engine tests."""
    x, q = reference._clustered(4000, 32, 96, 64, seed=3)
    idx = ivf_flat.build(ivf_flat.IndexParams(n_lists=32, seed=0), x)
    return {"x": x, "q": np.asarray(q), "idx": idx}


# -- keying ------------------------------------------------------------------

def test_shape_family_buckets():
    assert tune.shape_family(12_000, 64) == "10k-d64-bal"
    assert tune.shape_family(950_000, 128, "skew") == "1m-d128-skew"
    assert tune.shape_family(1_000, 48) == "1k-d64-bal"
    assert tune.shape_family(4_096, 33, "clump") == "10k-d32-clump"
    with pytest.raises(RaftError):
        tune.shape_family(100, 10, "weird")


def test_family_of_measures_balance(small):
    assert tune.family_of(small["idx"]) == "10k-d32-bal"
    # the heavytail reference family classifies skew (the r5 signature)
    xs, _ = reference._clustered(4000, 32, 16, 64, seed=5, heavytail=True)
    sidx = ivf_flat.build(ivf_flat.IndexParams(n_lists=32, seed=0), xs)
    assert tune.family_of(sidx).endswith("-skew")


def test_kind_of():
    bf = brute_force.BruteForce().build(np.zeros((8, 4), np.float32))
    assert tune.kind_of(bf) == "brute_force"
    with pytest.raises(RaftError):
        tune.kind_of(object())


# -- decision log ------------------------------------------------------------

def test_decision_log_roundtrip(tmp_path, small):
    dec = tune.Decision(kind="ivf_flat", dtype="float32",
                        family="10k-d32-bal", params={"n_probes": 4},
                        evidence={"recall_target": 0.9})
    log = tune.DecisionLog(meta={"round": "test"})
    log.add(dec)
    path = tmp_path / "log.json"
    log.save(str(path))
    log2 = tune.DecisionLog.load(str(path))
    assert len(log2) == 1 and log2.meta["round"] == "test"
    assert log2.get("ivf_flat", "float32", "10k-d32-bal").params == \
        {"n_probes": 4}
    # exact-family resolve
    assert log2.resolve(small["idx"]).key == dec.key
    # nearest-family fallback: a different-scale entry still resolves
    log3 = tune.DecisionLog()
    far = tune.Decision(kind="ivf_flat", dtype="float32",
                        family="1m-d32-bal", params={"n_probes": 16})
    log3.add(far)
    assert log3.resolve(small["idx"]).key == far.key
    # wrong kind never resolves
    log4 = tune.DecisionLog()
    log4.add(tune.Decision(kind="cagra", dtype="float32",
                           family="10k-d32-bal", params={}))
    assert log4.resolve(small["idx"]) is None


def test_decision_log_rejects_garbage():
    with pytest.raises(RaftError):
        tune.DecisionLog.from_json({"format": "something_else"})
    with pytest.raises(RaftError):
        tune.Decision.from_dict({"dtype": "float32"})


# -- sweep engine ------------------------------------------------------------

def test_sweep_chooses_and_records(small):
    from raft_tpu import obs

    before = obs.to_json()
    dec = tune.sweep(small["idx"], small["q"], k=5, dataset=small["x"],
                     grid=[{"n_probes": 8}, {"n_probes": 4},
                           {"n_probes": 16}],
                     recall_target="default", repeats=1)
    ev = dec.evidence
    assert dec.kind == "ivf_flat" and len(ev["trials"]) == 3
    # the acceptance rule: the grid head (incumbent) is feasible at its
    # own recall, so the chosen point matches-or-beats it on both axes
    assert ev["target_met"]
    assert ev["chosen_qps"] >= ev["default_qps"]
    assert ev["chosen_recall"] >= ev["recall_target"]
    assert ev["frontier"], ev
    assert dec.params in [t["params"] for t in ev["trials"]]
    # every trial is an obs event
    d = obs.delta(before, obs.to_json())
    assert d.get('raft_tpu_tune_trials_total'
                 '{family="10k-d32-bal",kind="ivf_flat"}') == 3


def test_sweep_infeasible_target_takes_best_recall(small):
    dec = tune.sweep(small["idx"], small["q"], k=5, dataset=small["x"],
                     grid=[{"n_probes": 2}, {"n_probes": 8}],
                     recall_target=2.0, repeats=1)
    ev = dec.evidence
    assert not ev["target_met"]
    best = max(t["recall"] for t in ev["trials"] if "recall" in t)
    assert ev["chosen_recall"] == best


def test_sweep_records_failed_arm_as_evidence(small):
    dec = tune.sweep(small["idx"], small["q"], k=5, dataset=small["x"],
                     grid=[{"n_probes": 8}, {"bogus_knob": 1}], repeats=1,
                     recall_target="default")
    trials = dec.evidence["trials"]
    assert "error" in trials[1] and "bogus_knob" in trials[1]["error"]
    assert dec.params == {"n_probes": 8}


def test_sweep_select_k_records_ineligible_on_cpu():
    dec = tune.sweep_select_k(rows=8, cols=(2048,), ks=(5,), repeats=1)
    assert dec.params["wide_cols_min"] == 65536  # the shipped default kept
    assert dec.evidence["pallas_measured"] is False
    errs = [t for t in dec.evidence["trials"] if "error" in t]
    assert errs and "ineligible" in errs[0]["error"]


# -- applying decisions ------------------------------------------------------

def test_tuned_search_params_mapping():
    sp, rr = tune.tuned_search_params(
        "ivf_pq", {"n_probes": 4, "refine_ratio": 8, "lut_dtype": "bfloat16"})
    assert sp.n_probes == 4 and sp.lut_dtype == "bfloat16" and rr == 8
    sp, rr = tune.tuned_search_params("cagra", {"itopk_size": 64})
    assert sp.itopk_size == 64 and rr == 1
    sp, rr = tune.tuned_search_params("brute_force", {})
    assert sp is None and rr == 1
    with pytest.raises(RaftError):  # unknown knob must never half-apply
        tune.tuned_search_params("ivf_flat", {"itopk_size": 32})
    with pytest.raises(RaftError):  # refine is an IVF epilogue only
        tune.tuned_search_params("cagra", {"refine_ratio": 4})


def test_make_searcher_refine_needs_rows(small):
    dec = tune.Decision(kind="ivf_flat", dtype="float32",
                        family="10k-d32-bal",
                        params={"n_probes": 4, "refine_ratio": 4})
    with pytest.raises(RaftError, match="raw rows"):
        tune.make_searcher(small["idx"], dec)
    hook = tune.make_searcher(small["idx"], dec, dataset=small["x"])
    assert hook.kind == "ivf_flat+refine" and hook.tuned == dec.key
    d, i = hook(small["q"][:4], 5)
    assert np.asarray(i).shape == (4, 5)


def test_attach_and_batched_searcher(small, tmp_path):
    idx = small["idx"]
    dec = tune.Decision(kind="ivf_flat", dtype="float32",
                        family="10k-d32-bal", params={"n_probes": 4})
    wrong = tune.Decision(kind="cagra", dtype="float32",
                          family="10k-d32-bal", params={})
    with pytest.raises(RaftError):
        tune.attach(idx, wrong)
    with pytest.raises(RaftError):  # bad knobs fail at pin time
        tune.attach(idx, tune.Decision(
            kind="ivf_flat", dtype="float32", family="10k-d32-bal",
            params={"nope": 1}))
    try:
        tune.attach(idx, dec)
        hook = ivf_flat.batched_searcher(idx)
        assert hook.tuned == dec.key
        # explicit params still win over the attached pin
        hook2 = ivf_flat.batched_searcher(
            idx, ivf_flat.SearchParams(n_probes=8))
        assert not hasattr(hook2, "tuned")
    finally:
        idx.tuned = None


def test_wide_cols_threshold_pin_and_env(monkeypatch):
    import jax.numpy as jnp

    from raft_tpu.matrix.select_k import (set_wide_cols_threshold,
                                          wide_cols_threshold,
                                          wide_dispatch_ok)

    try:
        assert wide_cols_threshold() == 65536
        set_wide_cols_threshold(1024)
        assert wide_cols_threshold() == 1024
        assert wide_dispatch_ok(2048, 10, jnp.float32, backend="tpu")
        set_wide_cols_threshold(None)
        assert not wide_dispatch_ok(2048, 10, jnp.float32, backend="tpu")
        monkeypatch.setenv("RAFT_TPU_WIDE_SELECT_COLS", "4096")
        assert wide_cols_threshold() == 4096
        with pytest.raises(RaftError):
            set_wide_cols_threshold(0)
    finally:
        set_wide_cols_threshold(None)


def test_apply_global_pins_select_threshold():
    from raft_tpu.matrix.select_k import (set_wide_cols_threshold,
                                          wide_cols_threshold)

    log = tune.DecisionLog()
    assert tune.apply_global(log) == {}
    log.add(tune.Decision(kind="select_k", dtype="float32", family="wide",
                          params={"wide_cols_min": 32768}))
    try:
        assert tune.apply_global(log) == {"select_k.wide_cols_min": 32768}
        assert wide_cols_threshold() == 32768
    finally:
        set_wide_cols_threshold(None)


def test_refine_and_ground_truth_follow_index_metric(rng):
    """An inner-product index must be swept against IP ground truth and
    refined by IP score — an L2 epilogue would silently re-rank wrong
    (code-review regression)."""
    d, n = 8, 64
    direction = np.zeros((1, d), np.float32)
    direction[0, 0] = 1.0
    scales = np.linspace(0.1, 10.0, n).astype(np.float32)
    x = scales[:, None] * direction + \
        0.01 * rng.standard_normal((n, d)).astype(np.float32)
    q = direction.copy()  # L2-nearest ~ scale 1.0; IP-max = scale 10
    gt_ip = _ground_truth(x, q, 1, metric="inner_product")
    gt_l2 = _ground_truth(x, q, 1)
    assert gt_ip[0, 0] == n - 1 and gt_l2[0, 0] != n - 1
    idx = ivf_flat.build(ivf_flat.IndexParams(
        n_lists=2, metric="inner_product", seed=0), x)
    fn = search_fn(idx, {"n_probes": 2, "refine_ratio": 4}, dataset=x)
    _, ids = fn(q, 1)
    assert int(np.asarray(ids)[0, 0]) == n - 1


def test_loaded_refine_pin_degrades_without_rows(small, tmp_path):
    """An attached refine_ratio pin must never make the no-params
    batched_searcher of a LOADED index crash: the refine-free remainder
    serves, with a warning (code-review regression)."""
    idx = small["idx"]
    dec = tune.Decision(kind="ivf_flat", dtype="float32",
                        family=tune.family_of(idx),
                        params={"n_probes": 4, "refine_ratio": 4})
    try:
        tune.attach(idx, dec)
        path = tmp_path / "pinned.bin"
        ivf_flat.save(idx, str(path))
        loaded = ivf_flat.load(str(path))
        hook = ivf_flat.batched_searcher(loaded)  # must not raise
        assert hook.kind == "ivf_flat" and hook.tuned == dec.key
        d, i = hook(small["q"][:2], 5)
        assert np.asarray(i).shape == (2, 5)
    finally:
        idx.tuned = None


def test_resolve_never_crosses_balance_class(small):
    """The fallback must not hand a skew-family pin to a balanced index:
    that transfer IS the measured r5 recall collapse (code-review
    regression)."""
    log = tune.DecisionLog()
    log.add(tune.Decision(kind="ivf_flat", dtype="float32",
                          family="10k-d32-skew", params={"n_probes": 32}))
    assert log.resolve(small["idx"]) is None


def test_resolve_tolerates_unstructured_family(small):
    """Hand-authored decisions (from_dict's 'any' family) resolve as a
    last resort instead of crashing the fallback scorer (code-review
    regression)."""
    log = tune.DecisionLog()
    log.add(tune.Decision.from_dict(
        {"kind": "ivf_flat", "params": {"n_probes": 16}}))
    dec = log.resolve(small["idx"])
    assert dec is not None and dec.family == "any"
    # a structured-family entry still wins over the unstructured one
    log.add(tune.Decision(kind="ivf_flat", dtype="float32",
                          family="1m-d32-bal", params={"n_probes": 8}))
    assert log.resolve(small["idx"]).family == "1m-d32-bal"


def test_select_k_sweep_counts_ineligible_trials():
    from raft_tpu import obs

    before = obs.to_json()
    dec = tune.sweep_select_k(rows=8, cols=(1024,), ks=(5,), repeats=1)
    d = obs.delta(before, obs.to_json())
    counted = d.get('raft_tpu_tune_trials_total'
                    '{family="wide",kind="select_k"}')
    assert counted == len(dec.evidence["trials"])


# -- serialize /9 + /8 back-compat ------------------------------------------

def _roundtrip(write, read, tmp_path, name):
    path = tmp_path / name
    with open(path, "wb") as f:
        write(f)
    with open(path, "rb") as f:
        return read(f)


def test_serialize_v9_roundtrip_all_kinds(tmp_path, small):
    x = np.asarray(small["x"])[:600]
    tuned = {"kind": None, "dtype": "float32", "family": "10k-d32-bal",
             "params": {}, "evidence": {"recall_target": 0.9}}

    bf = brute_force.BruteForce().build(x)
    bf.tuned = dict(tuned, kind="brute_force")
    out = _roundtrip(lambda f: brute_force.write_index(f, bf),
                     brute_force.read_index, tmp_path, "bf.bin")
    assert out.tuned == bf.tuned

    fidx = ivf_flat.build(ivf_flat.IndexParams(n_lists=8, seed=0), x)
    fidx.tuned = dict(tuned, kind="ivf_flat", params={"n_probes": 4})
    out = _roundtrip(lambda f: ivf_flat.write_index(f, fidx),
                     ivf_flat.read_index, tmp_path, "flat.bin")
    assert out.tuned == fidx.tuned

    pidx = ivf_pq.build(
        ivf_pq.IndexParams(n_lists=8, pq_bits=4, pq_dim=16, seed=0), x)
    pidx.tuned = dict(tuned, kind="ivf_pq",
                      params={"n_probes": 4, "refine_ratio": 4})
    out = _roundtrip(lambda f: ivf_pq.write_index(f, pidx),
                     ivf_pq.read_index, tmp_path, "pq.bin")
    assert out.tuned == pidx.tuned

    cidx = cagra.build(cagra.IndexParams(
        graph_degree=8, intermediate_graph_degree=16, seed=0), x[:300])
    cidx.tuned = dict(tuned, kind="cagra", params={"itopk_size": 16})
    out = _roundtrip(lambda f: cagra.write_index(f, cidx),
                     cagra.read_index, tmp_path, "cagra.bin")
    assert out.tuned == cidx.tuned

    # an untuned index writes no decision and reads back None
    cidx.tuned = None
    out = _roundtrip(lambda f: cagra.write_index(f, cidx),
                     cagra.read_index, tmp_path, "cagra2.bin")
    assert out.tuned is None


def test_serialize_v8_files_still_load(tmp_path, monkeypatch, small):
    """A writer pinned to raft_tpu/8 emits true /8 bytes (no tuned
    record); the /9 reader must load them untuned — full /8 read-compat."""
    x = np.asarray(small["x"])[:400]
    fidx = ivf_flat.build(ivf_flat.IndexParams(n_lists=8, seed=0), x)
    fidx.tuned = {"kind": "ivf_flat", "dtype": "float32", "family": "f",
                  "params": {"n_probes": 4}}
    monkeypatch.setattr(serialize, "SERIALIZATION_VERSION", "raft_tpu/8")
    path = tmp_path / "v8.bin"
    with open(path, "wb") as f:
        ivf_flat.write_index(f, fidx)
    monkeypatch.undo()
    with open(path, "rb") as f:
        out = ivf_flat.read_index(f)
    assert out.tuned is None
    assert out.data_kind == fidx.data_kind
    np.testing.assert_array_equal(np.asarray(out.list_sizes),
                                  np.asarray(fidx.list_sizes))


def test_version_number_helper():
    assert serialize.version_number("raft_tpu/9") == 9
    assert serialize.version_number(serialize.SERIALIZATION_VERSION) >= 9
    with pytest.raises(ValueError):
        serialize.version_number("garbage")


def test_stream_save_preserves_sealed_tuned(tmp_path, small):
    """The sealed index's pin rides the stream section's embedded
    serializer (docs/streaming.md)."""
    from raft_tpu import stream

    idx = ivf_flat.build(ivf_flat.IndexParams(n_lists=8, seed=0),
                         np.asarray(small["x"])[:400])
    idx.tuned = {"kind": "ivf_flat", "dtype": "float32",
                 "family": "10k-d32-bal", "params": {"n_probes": 4}}
    m = stream.MutableIndex(idx, delta_capacity=16)
    path = tmp_path / "stream.bin"
    stream.save(m, str(path))
    m2 = stream.load(str(path))
    assert m2._state.sealed.tuned == idx.tuned


# -- the committed artifact --------------------------------------------------

def test_artifact_acceptance_properties():
    """TUNE_r08.json: every entry's chosen point matches-or-beats its
    grid-head hand-picked point (QPS at equal-or-better recall) — the
    ROADMAP item-5 done-bar, asserted from the artifact's own numbers."""
    with open(ARTIFACT) as f:
        artifact = json.load(f)
    log = tune.DecisionLog.from_json(artifact)
    assert artifact["meta"]["round"] == reference.ROUND
    kinds = {d.kind for d in log.entries()}
    assert {"ivf_flat", "ivf_pq", "cagra", "select_k"} <= kinds
    # both families of the non-transfer result are pinned separately
    assert log.get("ivf_pq", "float32", "10k-d64-bal") is not None
    assert log.get("ivf_pq", "float32", "10k-d64-skew") is not None
    for dec in log.entries():
        ev = dec.evidence
        if dec.kind == "select_k":
            assert "pallas_measured" in ev and ev["trials"]
            continue
        assert ev["target_met"], dec.key
        assert ev["chosen_qps"] >= ev["default_qps"], dec.key
        assert ev["chosen_recall"] >= ev["recall_target"], dec.key
        assert dec.params in [t["params"] for t in ev["trials"]
                              if "error" not in t], dec.key
        assert ev["default_params"] == ev["trials"][0]["params"], dec.key


def _drift_check(name, tol=0.03):
    """Rebuild a reference family and re-measure the committed chosen and
    default operating points' recall (seeded generators on CPU: the only
    legitimate movement is a code change — which is the point)."""
    with open(ARTIFACT) as f:
        log = tune.DecisionLog.from_json(json.load(f))
    fam = reference.build_family(name)
    idx, q, x, k = (fam["index"], np.asarray(fam["queries"]),
                    fam["dataset"], fam["k"])
    entry = log.resolve(idx, x)
    assert entry is not None, f"no artifact entry resolves for {name}"
    gt = _ground_truth(x, q, k)
    recorded = {json.dumps(t["params"], sort_keys=True): t["recall"]
                for t in entry.evidence["trials"] if "error" not in t}
    for params in (entry.params, entry.evidence["default_params"]):
        fn = search_fn(idx, dict(params), dataset=x)
        _, ids = fn(q, k)
        got = _recall(np.asarray(ids), gt)
        want = recorded[json.dumps(params, sort_keys=True)]
        assert abs(got - want) <= tol, (
            f"{name} drifted: {params} measured {got:.4f} vs committed "
            f"{want:.4f} — regenerate TUNE_r08.json (bench/tune_sweep.py "
            "--cpu-mesh) and record why in BASELINE.md")
        if params == entry.params:
            assert got >= entry.evidence["recall_target"] - tol


def test_artifact_drift_pin_ivf_flat():
    _drift_check("ivf_flat_bal")


def test_artifact_drift_pin_ivf_pq():
    _drift_check("ivf_pq_bal")


@pytest.mark.parametrize("name", ["ivf_pq_skew", "cagra_bal"])
def test_artifact_drift_pin_heavy(name):
    # cagra rebuild + the heavytail family are the slow half (slow manifest)
    _drift_check(name)
