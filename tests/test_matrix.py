"""Matrix ops + select_k tests (reference analogue: cpp/test/matrix/*, MATRIX_TEST;
select_k harness cpp/internal/raft_internal/matrix/select_k.cuh)."""

import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu import matrix
from raft_tpu.core import RaftError


class TestSelectK:
    @pytest.mark.parametrize("k", [1, 5, 16])
    @pytest.mark.parametrize("select_min", [True, False])
    def test_matches_numpy(self, rng, k, select_min):
        v = rng.random((13, 50)).astype(np.float32)
        vals, idx = matrix.select_k(v, k, select_min=select_min)
        order = np.argsort(v if select_min else -v, axis=1)[:, :k]
        np.testing.assert_allclose(
            np.sort(np.asarray(vals), axis=1),
            np.sort(np.take_along_axis(v, order, 1), axis=1),
            rtol=1e-6,
        )
        # indices must address the selected values
        np.testing.assert_allclose(
            np.take_along_axis(v, np.asarray(idx), 1), np.asarray(vals), rtol=1e-6
        )

    def test_payload_indices(self, rng):
        v = rng.random((4, 20)).astype(np.float32)
        payload = rng.integers(0, 10_000, (4, 20)).astype(np.int32)
        vals, idx = matrix.select_k(v, 3, indices=payload)
        pos = np.argsort(v, axis=1)[:, :3]
        got = np.sort(np.asarray(idx), axis=1)
        want = np.sort(np.take_along_axis(payload, pos, 1), axis=1)
        np.testing.assert_array_equal(got, want)

    def test_k_equals_n(self, rng):
        v = rng.random((3, 8)).astype(np.float32)
        vals, idx = matrix.select_k(v, 8)
        np.testing.assert_allclose(np.asarray(vals), np.sort(v, axis=1), rtol=1e-6)

    def test_k_out_of_range(self):
        with pytest.raises(RaftError):
            matrix.select_k(np.zeros((2, 4)), 5)
        with pytest.raises(RaftError):
            matrix.select_k(np.zeros((2, 4)), 0)

    @pytest.mark.parametrize("dt", [np.int32, np.int8, np.uint8, np.uint32])
    @pytest.mark.parametrize("select_min", [True, False])
    def test_integer_values_exact(self, rng, dt, select_min):
        """Integer scores (exact int32 distances from the s8 search paths,
        byte payload matrices) rank exactly — unsigned flips about the
        dtype max, signed sub-32-bit widens before negation — and keep
        their dtype and magnitudes in the output values."""
        info = np.iinfo(dt)
        # full-range draws so the wrap hazards (negation at INT_MIN, the
        # uint flip) are actually on the board — and both extremes pinned
        # deterministically (a random draw almost never lands INT32_MIN)
        v = rng.integers(info.min, int(info.max) + 1, (9, 40)).astype(dt)
        v[0, 3], v[0, 7] = info.min, info.max
        vals, idx = matrix.select_k(v, 7, select_min=select_min)
        assert np.asarray(vals).dtype == dt
        sv = np.sort(v.astype(np.int64), axis=1)
        want = sv[:, :7] if select_min else sv[:, ::-1][:, :7]
        np.testing.assert_array_equal(
            np.sort(np.asarray(vals).astype(np.int64), axis=1),
            np.sort(want, axis=1))
        # indices must address the selected values
        np.testing.assert_array_equal(
            np.take_along_axis(v, np.asarray(idx), 1), np.asarray(vals))


class TestOps:
    def test_argmax_argmin(self, rng):
        m = rng.random((10, 7)).astype(np.float32)
        np.testing.assert_array_equal(np.asarray(matrix.argmax(m)), m.argmax(1))
        np.testing.assert_array_equal(np.asarray(matrix.argmin(m)), m.argmin(1))

    def test_gather(self, rng):
        m = rng.random((10, 4)).astype(np.float32)
        ids = np.array([3, 1, 7])
        np.testing.assert_array_equal(np.asarray(matrix.gather(m, ids)), m[ids])

    def test_gather_if(self, rng):
        m = rng.random((10, 4)).astype(np.float32)
        ids = np.array([0, 1, 2])
        mask = np.array([True, False, True])
        out = np.asarray(matrix.gather_if(m, ids, mask))
        np.testing.assert_array_equal(out[0], m[0])
        np.testing.assert_array_equal(out[1], np.zeros(4))

    def test_slice(self, rng):
        m = rng.random((6, 6)).astype(np.float32)
        np.testing.assert_array_equal(np.asarray(matrix.slice(m, 1, 4, 2, 5)), m[1:4, 2:5])

    def test_col_wise_sort(self, rng):
        m = rng.random((5, 9)).astype(np.float32)
        s, order = matrix.col_wise_sort(m)
        np.testing.assert_allclose(np.asarray(s), np.sort(m, axis=1), rtol=1e-6)
        np.testing.assert_array_equal(np.take_along_axis(m, np.asarray(order), 1), np.asarray(s))

    def test_linewise_op(self, rng):
        m = rng.random((4, 6)).astype(np.float32)
        v = rng.random(6).astype(np.float32)
        out = np.asarray(matrix.linewise_op(m, v, along_rows=True, op=jnp.add))
        np.testing.assert_allclose(out, m + v[None, :], rtol=1e-6)

    def test_sign_flip(self, rng):
        m = rng.standard_normal((8, 3)).astype(np.float32)
        out = np.asarray(matrix.sign_flip(m))
        piv = np.take_along_axis(out, np.abs(out).argmax(0)[None, :], 0)
        assert (piv >= 0).all()
        np.testing.assert_allclose(np.abs(out), np.abs(m), rtol=1e-6)

    def test_triangular_diagonal(self, rng):
        m = rng.random((5, 5)).astype(np.float32)
        np.testing.assert_array_equal(np.asarray(matrix.upper_triangular(m)), np.triu(m))
        np.testing.assert_array_equal(np.asarray(matrix.get_diagonal(m)), np.diag(m))
        out = np.asarray(matrix.set_diagonal(m, np.zeros(5)))
        np.testing.assert_allclose(np.diag(out), 0.0)

    def test_reverse(self, rng):
        m = rng.random((4, 5)).astype(np.float32)
        np.testing.assert_array_equal(np.asarray(matrix.reverse(m)), m[:, ::-1])
        np.testing.assert_array_equal(np.asarray(matrix.reverse(m, along_rows=False)), m[::-1])


class TestWideDispatch:
    """The r06 dispatch lift (k <= 256) and its guard rails: the cap must
    track the kernel's documented limit, and the predicate is the single
    dispatch rule shared by select_k and the in-jit ivf_pq selects."""

    def test_dispatch_cap_matches_kernel_limit(self):
        from raft_tpu.matrix.select_k import SELECT_K_DISPATCH_MAX_K
        from raft_tpu.ops.topk import TOPK_MAX_K

        # a drift here means select_k promises a k the kernel rejects (or
        # silently under-dispatches a lifted kernel limit)
        assert SELECT_K_DISPATCH_MAX_K == TOPK_MAX_K == 256

    def test_wide_dispatch_predicate(self):
        from raft_tpu.matrix.select_k import wide_dispatch_ok

        ok = lambda n, k, dt: wide_dispatch_ok(n, k, dt, backend="tpu")
        assert ok(65536, 128, jnp.float32)
        assert ok(65536, 193, jnp.float32)      # the CAGRA build-chunk k
        assert ok(65536, 256, jnp.float32)      # r06 lift: full kernel range
        assert not ok(65536, 257, jnp.float32)  # beyond the kernel
        assert not ok(65535, 256, jnp.float32)  # below the measured regime
        assert not ok(65536, 256, jnp.int32)    # integer ranking is exact-only
        assert not wide_dispatch_ok(65536, 256, jnp.float32, backend="cpu")

    def test_env_cap_escape_hatch(self, monkeypatch):
        """RAFT_TPU_WIDE_SELECT_CAP re-imposes the r05 cap if a toolchain
        regresses (documented in bench/topk_chain_repro.py)."""
        from raft_tpu.matrix.select_k import wide_dispatch_ok

        monkeypatch.setenv("RAFT_TPU_WIDE_SELECT_CAP", "128")
        assert wide_dispatch_ok(65536, 128, jnp.float32, backend="tpu")
        assert not wide_dispatch_ok(65536, 129, jnp.float32, backend="tpu")

    def test_select_k_impl_forced_pallas_matches_xla(self, rng):
        """The in-jit routed selector (ivf_pq's candidate selects): forced
        'pallas' must agree with lax.top_k exactly, payload included."""
        from raft_tpu.matrix.select_k import _select_k, select_k_impl

        x = jnp.asarray(rng.random((6, 900)).astype(np.float32))
        idx = jnp.asarray(rng.integers(0, 10_000, (6, 900)).astype(np.int32))
        for select_min in (True, False):
            v0, i0 = _select_k(x, idx, 70, select_min)
            v1, i1 = select_k_impl(x, idx, 70, select_min, impl="pallas")
            np.testing.assert_allclose(np.asarray(v1), np.asarray(v0), atol=0)
            np.testing.assert_array_equal(np.asarray(i1), np.asarray(i0))

    def test_select_k_impl_rejects_int_pallas(self, rng):
        from raft_tpu.matrix.select_k import select_k_impl

        x = jnp.asarray(rng.integers(0, 100, (4, 300)).astype(np.int32))
        with pytest.raises(RaftError, match="integer"):
            select_k_impl(x, None, 5, True, impl="pallas")
