"""Online quality observability (ISSUE 8): recall canary + Wilson interval,
family-drift detection, SLO burn rates, request-level tracing, and the
routed HTTP endpoints.

Deterministic throughout: injected clocks (no wall-clock sleeps in
assertions), seeded canary sampling, and the tune/reference data generator
for the drift families. Tests that read the DEFAULT registry diff
to_json() snapshots, same as test_obs.py.
"""

import json
import math
import urllib.error
import urllib.request

import numpy as np
import pytest

from raft_tpu import obs
from raft_tpu.obs import quality, requestlog, slo


@pytest.fixture(autouse=True)
def _metrics_enabled():
    obs.enable()
    yield
    obs.enable()


# ---------------------------------------------------------------------------
# Wilson interval
# ---------------------------------------------------------------------------


@pytest.mark.quality
class TestWilson:
    def test_golden_values(self):
        # classic reference point: 95/100 at z=1.96 -> (0.888, 0.978)
        lo, hi = quality.wilson_interval(95, 100)
        assert lo == pytest.approx(0.8882, abs=5e-4)
        assert hi == pytest.approx(0.9785, abs=5e-4)

    def test_stays_in_unit_interval_at_extremes(self):
        assert quality.wilson_interval(0, 50)[0] == 0.0
        lo, hi = quality.wilson_interval(50, 50)
        assert hi == 1.0 and 0.9 < lo < 1.0  # p=1 still gets a real lower CI

    def test_no_trials_is_vacuous(self):
        assert quality.wilson_interval(0, 0) == (0.0, 1.0)

    def test_narrows_with_samples(self):
        w100 = quality.wilson_interval(90, 100)
        w10000 = quality.wilson_interval(9000, 10000)
        assert (w10000[1] - w10000[0]) < (w100[1] - w100[0]) / 5


# ---------------------------------------------------------------------------
# canary core (fake oracle: exact bookkeeping, no device work)
# ---------------------------------------------------------------------------


def _fake_oracle(answers, dim=4):
    """Oracle returning fixed ids regardless of the query — lets the test
    pin the exact match count."""

    def fn(queries, k):
        q = np.asarray(queries)
        ids = np.tile(np.asarray(answers[:k], np.int32), (q.shape[0], 1))
        return np.zeros((q.shape[0], k), np.float32), ids

    fn.dim = dim
    fn.query_dtype = "float32"
    return fn


@pytest.mark.quality
class TestCanaryCore:
    def test_estimate_and_interval_with_known_overlap(self):
        # oracle says (0,1,2,3); served ids overlap 3 of 4 -> recall 0.75
        canary = quality.RecallCanary(
            _fake_oracle([0, 1, 2, 3]), k=4, sample_rate=1.0,
            buckets=(1, 2, 4), name="t-core", seed=0)
        q = np.zeros((20, 4), np.float32)
        served = np.tile(np.array([0, 1, 2, 99], np.int32), (20, 1))
        before = obs.to_json()
        assert canary.offer(q, served) == 20
        assert canary.drain() == 20
        est = canary.estimate()
        assert est["recall"] == pytest.approx(0.75)
        assert est["scored_slots"] == 80 and est["reranked"] == 20
        assert est["wilson_low"] < 0.75 < est["wilson_high"]
        assert canary.in_interval(0.75)
        assert not canary.in_interval(0.2)
        d = obs.delta(before, obs.to_json())
        assert d['raft_tpu_quality_canary_sampled_total{name="t-core"}'] == 20
        assert d['raft_tpu_quality_canary_reranked_total{name="t-core"}'] == 20
        # per-query recall histogram: 0.75 lands in the (0.7, 0.8] ratio
        # bucket, with labels preserved in the flattened view
        key = ('raft_tpu_quality_canary_recall_bucket'
               '{le="0.8",name="t-core"}')
        assert d[key] == 20, d
        assert obs.quantile("raft_tpu_quality_canary_recall", 0.5,
                            name="t-core") == pytest.approx(0.75, abs=0.06)

    def test_zero_rate_is_one_compare(self):
        canary = quality.RecallCanary(_fake_oracle([0]), k=1,
                                      sample_rate=0.0, name="t-off")
        assert canary.offer(np.zeros((8, 4), np.float32),
                            np.zeros((8, 1), np.int32)) == 0
        assert canary.pending() == 0 and canary.drain() == 0

    def test_reservoir_bounds_memory_and_counts_drops(self):
        canary = quality.RecallCanary(
            _fake_oracle([0, 1]), k=2, sample_rate=1.0, reservoir=8,
            buckets=(1, 2, 4, 8), name="t-res", seed=1)
        before = obs.to_json()
        canary.offer(np.zeros((50, 4), np.float32),
                     np.zeros((50, 2), np.int32))
        assert canary.pending() == 8  # bounded
        d = obs.delta(before, obs.to_json())
        assert d['raft_tpu_quality_canary_dropped_total{name="t-res"}'] == 42
        assert canary.drain() == 8

    def test_sampling_rate_is_respected(self):
        canary = quality.RecallCanary(
            _fake_oracle([0]), k=1, sample_rate=0.1, reservoir=10_000,
            name="t-rate", seed=7)
        kept = canary.offer(np.zeros((5000, 4), np.float32),
                            np.zeros((5000, 1), np.int32))
        assert 350 < kept < 650  # ~500 expected; seeded, so stable

    def test_padded_tail_results_are_discarded(self):
        # 3 queries through a (1,2,4) ladder: one bucket-4 dispatch padded
        # by a repeated row; the estimate must count exactly 3 queries
        canary = quality.RecallCanary(
            _fake_oracle([5, 6]), k=2, sample_rate=1.0, buckets=(1, 2, 4),
            name="t-pad", seed=0)
        canary.offer(np.zeros((3, 4), np.float32),
                     np.tile(np.array([5, 9], np.int32), (3, 1)))
        assert canary.drain() == 3
        est = canary.estimate()
        assert est["scored_slots"] == 6
        assert est["recall"] == pytest.approx(0.5)


# ---------------------------------------------------------------------------
# canary end-to-end: exact oracle over a MutableIndex + the service tap
# ---------------------------------------------------------------------------


def _small_stack(rng, n=600, d=16, k=5, delta_capacity=64, **svc_kw):
    from raft_tpu import stream
    from raft_tpu.neighbors import ivf_flat
    from raft_tpu.serve import SearchService

    x = rng.random((n, d), dtype=np.float32)
    idx = ivf_flat.build(ivf_flat.IndexParams(n_lists=8, seed=0), x)
    m = stream.MutableIndex(
        idx, search_params=ivf_flat.SearchParams(n_probes=8), dataset=x,
        index_params=ivf_flat.IndexParams(n_lists=8, seed=0),
        delta_capacity=delta_capacity, name="q")
    svc = SearchService(max_batch=8, start_workers=False, **svc_kw)
    svc.publish("q", m, k=k)
    return x, m, svc


@pytest.mark.quality
def test_exact_search_matches_fresh_brute_force(rng):
    """MutableIndex.exact_search IS the exact kNN over the live rows:
    bit-equal ids vs a fresh brute-force scan of exactly the live set,
    across upserts, deletes and a compaction."""
    from raft_tpu.neighbors.brute_force import knn

    x, m, svc = _small_stack(rng)
    q = rng.random((16, 16), dtype=np.float32)
    new = rng.random((10, 16), dtype=np.float32)
    gids = m.upsert(new)
    m.delete(np.arange(7))

    def oracle_ids():
        live = np.concatenate([x[7:], new])
        live_gids = np.concatenate([np.arange(7, 600), gids])
        _, pos = knn(live, q, 5)
        return live_gids[np.asarray(pos)]

    _, got = m.exact_search(q, 5)
    np.testing.assert_array_equal(np.asarray(got), oracle_ids())
    m.compact()  # fold the delta; exact view must be unchanged
    _, got2 = m.exact_search(q, 5)
    np.testing.assert_array_equal(np.asarray(got2), oracle_ids())


@pytest.mark.quality
def test_exact_search_requires_store(rng):
    from raft_tpu import stream
    from raft_tpu.core.errors import RaftError
    from raft_tpu.neighbors import ivf_flat

    x = rng.random((200, 8), dtype=np.float32)
    idx = ivf_flat.build(ivf_flat.IndexParams(n_lists=4, seed=0), x)
    m = stream.MutableIndex(idx, retain_vectors=False, name="nostore")
    with pytest.raises(RaftError, match="retained row store"):
        m.exact_search(x[:2], 3)


@pytest.mark.quality
def test_canary_through_service_brackets_offline_recall(rng):
    """The full tap: SearchService(canary=) samples flushes, the drain
    reranks against the live corpus, and the offline recall of the same
    served pipeline lands inside the Wilson interval."""
    x, m, svc = _small_stack(rng)
    canary = quality.RecallCanary(
        quality.exact_oracle(m), k=5, sample_rate=1.0, reservoir=512,
        buckets=(1, 2, 4, 8), name="q", seed=3)
    svc._canary = canary  # wired post-construction to reuse _small_stack
    q = rng.random((48, 16), dtype=np.float32)
    futs = [svc.submit("q", q[i:i + 1], 5) for i in range(48)]
    while svc.pump(force=True):
        pass
    served = np.concatenate([np.asarray(f.result()[1]) for f in futs])
    assert canary.pending() == 48
    assert canary.drain() == 48
    # offline truth on the same queries (corpus unchanged since serving)
    _, oids = m.exact_search(q, 5)
    oids = np.asarray(oids)
    offline = float(np.mean([
        len(set(served[i]) & set(oids[i])) / 5 for i in range(48)]))
    est = canary.estimate()
    assert est["recall"] == pytest.approx(offline, abs=1e-9)
    assert canary.in_interval(offline)


@pytest.mark.quality
def test_canary_tap_only_samples_its_own_name(rng):
    """A service serving several names must not feed another stream's
    results to the canary's oracle."""
    from raft_tpu.neighbors import brute_force
    from raft_tpu.serve import SearchService

    x = rng.random((100, 8), dtype=np.float32)
    y = rng.random((100, 8), dtype=np.float32)
    bf_x = brute_force.BruteForce().build(x)
    bf_y = brute_force.BruteForce().build(y)
    canary = quality.RecallCanary(
        quality.exact_oracle(bf_x, dataset=x), k=3, sample_rate=1.0,
        buckets=(1, 2), name="xname")
    svc = SearchService(max_batch=2, start_workers=False, canary=canary)
    svc.publish("xname", bf_x, k=3)
    svc.publish("other", bf_y, k=3)
    fx = svc.submit("xname", x[:1], 3)
    fy = svc.submit("other", y[:1], 3)
    while svc.pump(force=True):
        pass
    fx.result(), fy.result()
    assert canary.pending() == 1  # only the xname flush was offered


@pytest.mark.quality
def test_canary_under_churn_tracks_oracle_with_zero_compiles(rng):
    """The ISSUE 8 integration bar: upserts + deletes + a mid-load
    compaction swap under an injected clock; the canary's estimate tracks
    a fresh-oracle measurement within its Wilson interval, and the whole
    monitored window — sampling, drains, the swap — attributes ZERO cold
    compiles (rehearsal-warmed, same discipline as the churn bench)."""
    from raft_tpu import stream
    from raft_tpu.neighbors import ivf_flat
    from raft_tpu.neighbors.brute_force import knn
    from raft_tpu.obs import compile as obs_compile
    from raft_tpu.serve import SearchService

    if not obs_compile.install():  # pragma: no cover - ancient jax
        pytest.skip("jax.monitoring unavailable")

    n, d, k, cap = 600, 16, 5, 64
    x = rng.random((n, d), dtype=np.float32)
    churn = rng.random((96, d), dtype=np.float32)
    q = rng.random((32, d), dtype=np.float32)
    ip = ivf_flat.IndexParams(n_lists=8, seed=0)
    sp = ivf_flat.SearchParams(n_probes=8)
    steps, ups, dels = 6, 16, 4

    def schedule(m, svc, canary, sample_box=None):
        for step in range(steps):
            lo, dlo = step * ups, step * dels
            m.upsert(churn[lo:lo + ups], ids=n + np.arange(lo, lo + ups))
            m.delete(np.arange(dlo, dlo + dels))
            if m.stats()["delta_fill"] >= 0.75:
                m.compact()
                svc.publish("churn", m.searcher(), k=k)
                canary.warm()
            # serve a few queries at warmed bucket shapes; the flush tap
            # samples them, the drain reranks immediately (the corpus is
            # frozen between offer and drain, so the estimate is clean)
            qs = q[(step * 8) % 32:(step * 8) % 32 + 8]
            fut = svc.submit("churn", qs, k)
            while svc.pump(force=True):
                pass
            if sample_box is not None:
                sample_box.append((np.asarray(fut.result()[1]),
                                   qs, m.size))
            else:
                fut.result()
            canary.drain()

    def build_stack(name):
        m = stream.MutableIndex(ivf_flat.build(ip, x), search_params=sp,
                                dataset=x, index_params=ip,
                                delta_capacity=cap, name=name)
        canary = quality.RecallCanary(
            quality.exact_oracle(m), k=k, sample_rate=1.0, reservoir=512,
            buckets=(1, 2, 4, 8), name="churn", seed=5)
        svc = SearchService(max_batch=8, start_workers=False, canary=canary)
        svc.publish("churn", m, k=k)
        m.warm(svc.buckets, ks=(k,))
        canary.warm()
        return m, canary, svc

    # rehearsal: compiles every epoch's program set (deterministic schedule)
    m0, canary0, svc0 = build_stack("rehearsal")
    schedule(m0, svc0, canary0)
    del m0, canary0, svc0

    # the attributed live window
    m, canary, svc = build_stack("live")
    samples = []
    with obs_compile.attribution() as rec:
        schedule(m, svc, canary, samples)
    assert rec.compile_s == 0.0 and rec.cache_misses == 0, rec.summary()

    est = canary.estimate()
    assert est["reranked"] == steps * 8 and est["scored_slots"] > 0
    # fresh-oracle offline recall: every step's served results vs a fresh
    # exact kNN over exactly that step's live rows (an independent
    # implementation of the canary's oracle — the bar is the BRACKETING:
    # the live estimate's Wilson interval must contain the offline truth
    # measured over the same window)
    matched = scored = 0
    for step, (served, qs, _) in enumerate(samples):
        del_done, ins_done = (step + 1) * dels, (step + 1) * ups
        live = np.concatenate([x[del_done:], churn[:ins_done]])
        live_gids = np.concatenate([np.arange(del_done, n),
                                    n + np.arange(ins_done)])
        _, pos = knn(live, qs, k)
        gt = live_gids[np.asarray(pos)]
        for i in range(len(qs)):
            matched += len(set(served[i]) & set(gt[i]))
            scored += k
    offline = matched / scored
    assert canary.in_interval(offline), (est, offline)
    # and the estimate itself is quality signal, not noise: uniform data
    # at k=5 has tight f32 margins, so the served recall sits high but
    # below 1.0 — the canary resolves that gap online
    assert 0.85 < est["recall"] <= 1.0, est


# ---------------------------------------------------------------------------
# drift detection (tier-1 acceptance: heavytail fires, isotropic silent)
# ---------------------------------------------------------------------------


@pytest.mark.quality
class TestDrift:
    def _rows(self, heavytail, n=2000, d=32, ncl=64):
        from raft_tpu.tune.reference import _clustered

        x, _ = _clustered(n, d, 8, ncl, seed=29 if heavytail else 23,
                          heavytail=heavytail)
        return np.asarray(x)

    def test_heavytail_fires_isotropic_stays_silent(self):
        from raft_tpu.tune import shape_family

        pinned = shape_family(2000, 32, "bal")
        iso, hot = self._rows(False), self._rows(True)
        before = obs.to_json()

        det = quality.DriftDetector(pinned, name="drift-iso", min_rows=256)
        det.offer_rows(iso[:1024])
        rep = det.check()
        assert rep is not None and not rep["drifted"], rep
        assert det.events == []

        det2 = quality.DriftDetector(pinned, name="drift-hot", min_rows=256)
        det2.offer_rows(hot[:1024])
        rep2 = det2.check()
        assert rep2 is not None and rep2["drifted"], rep2
        assert rep2["observed"].endswith("-skew")
        assert len(det2.events) == 1
        ev = det2.events[0]
        assert ev["event"] == "retune_advised"
        assert ev["auto_apply"] is False  # never auto-apply across classes
        d = obs.delta(before, obs.to_json())
        assert d.get(
            'raft_tpu_quality_retune_advised_total{name="drift-hot"}') == 1
        assert d.get(
            'raft_tpu_quality_family_drift{name="drift-hot"}') == 1.0
        # gauge stays 0 for the silent twin (delta drops unchanged zeros —
        # read the snapshot instead)
        snap = obs.snapshot()["raft_tpu_quality_family_drift"]["series"]
        by = {s["labels"]["name"]: s["value"] for s in snap}
        assert by["drift-iso"] == 0.0

    def test_event_fires_once_per_transition(self):
        from raft_tpu.tune import shape_family

        hot = self._rows(True)
        det = quality.DriftDetector(shape_family(2000, 32, "bal"),
                                    name="drift-once", min_rows=128)
        det.offer_rows(hot[:512])
        det.check()
        det.check()  # still drifted: no second event
        assert len(det.events) == 1
        iso = self._rows(False)
        det2 = quality.DriftDetector(shape_family(2000, 32, "bal"),
                                     name="drift-flap", min_rows=128)
        det2.offer_rows(hot[:512])
        det2.check()  # query feed drifts
        rep = det2.check(rows=iso, n_rows=2000, dim=32, source="compaction")
        assert not rep["drifted"]  # the corpus feed itself is clean...
        # ...but drift state is PER FEED: a clean corpus check must not
        # clear the standing query-side drift (the early-warning case)
        assert det2.drifted()
        assert len(det2.events) == 1  # and must not re-arm the event
        det2.offer_rows(hot[:512])
        det2.check()  # query feed still drifted: no new event
        assert len(det2.events) == 1
        det2.check(rows=hot, n_rows=2000, dim=32, source="compaction")
        assert len(det2.events) == 2  # corpus-feed transition: new event

    def test_below_min_rows_withholds_judgement(self):
        det = quality.DriftDetector("10k-d32-bal", min_rows=256)
        det.offer_rows(np.zeros((10, 32), np.float32))
        assert det.check() is None

    def test_corpus_feed_sees_size_decade_drift(self):
        iso = self._rows(False)
        det = quality.DriftDetector("100k-d32-bal", name="drift-size")
        rep = det.check(rows=iso, n_rows=2000, dim=32, source="compaction")
        assert rep["drifted"] and rep["observed"].startswith("1k-")

    def test_compactor_feeds_corpus_stats(self, rng):
        from raft_tpu import stream
        from raft_tpu.neighbors import ivf_flat
        from raft_tpu.tune import shape_family

        x = rng.random((600, 16), dtype=np.float32)
        idx = ivf_flat.build(ivf_flat.IndexParams(n_lists=8, seed=0), x)
        m = stream.MutableIndex(
            idx, search_params=ivf_flat.SearchParams(n_probes=8), dataset=x,
            index_params=ivf_flat.IndexParams(n_lists=8, seed=0),
            delta_capacity=64, name="dc")
        det = quality.DriftDetector(shape_family(600, 16, "bal"), name="dc")
        comp = stream.Compactor(m, drift=det)
        m.upsert(rng.random((8, 16), dtype=np.float32))
        report = comp.run_once(force=True)
        assert report["drift"] is not None
        assert report["drift"]["source"] == "compaction"
        assert not report["drift"]["drifted"]  # same family: silent


# ---------------------------------------------------------------------------
# SLO tracker: golden burn-rate math + status transitions (injected clock)
# ---------------------------------------------------------------------------


@pytest.mark.quality
class TestSLO:
    def _tracker(self, **pol):
        clk = [0.0]
        policy = slo.SLOPolicy(slot_s=30.0, windows_s=(300.0, 3600.0), **pol)
        return clk, slo.SLOTracker(policy, name="t", clock=lambda: clk[0])

    def test_burn_rate_golden(self):
        clk, t = self._tracker()
        for _ in range(950):
            t.record_admission(True)
        for _ in range(50):
            t.record_admission(False)
        # bad fraction 0.05 over a 0.001 budget -> burn exactly 50
        assert t.burn_rate("availability", 300.0) == pytest.approx(50.0)
        assert t.burn_rate("availability", 3600.0) == pytest.approx(50.0)
        # latency: 99 under the bound + 1 over at target 0.99 -> burn 1.0
        for _ in range(99):
            t.record_request(0.01, 0.05)
        t.record_request(0.5, 0.05)
        assert t.burn_rate("latency", 300.0) == pytest.approx(1.0)
        # quality: 450/500 matched at floor 0.9 -> miss 0.1 / budget 0.1
        t.record_quality(450, 500)
        assert t.burn_rate("quality", 300.0) == pytest.approx(1.0)

    def test_window_expiry_under_injected_clock(self):
        clk, t = self._tracker()
        for _ in range(10):
            t.record_admission(False)
        assert t.burn_rate("availability", 300.0) > 0
        clk[0] = 400.0  # past the short window, inside the long one
        assert t.burn_rate("availability", 300.0) == 0.0
        assert t.burn_rate("availability", 3600.0) > 0
        clk[0] = 4000.0  # everything expired
        assert t.burn_rate("availability", 3600.0) == 0.0

    def test_ready_to_degraded_on_recall_burn(self):
        """The acceptance transition: /healthz flips ready -> degraded when
        the recall SLO burn rate crosses the threshold."""
        clk, t = self._tracker(degraded_burn=1.0, failing_burn=100.0)
        assert t.status() == "ready"  # no events, no burn
        t.record_quality(990, 1000)   # miss 0.01 < budget 0.1: fine
        assert t.status() == "ready"
        t.record_quality(500, 1000)   # cumulative miss ~0.255: burn ~2.5
        assert t.status() == "degraded"
        code, body = t.healthz()
        assert code == 200 and body["status"] == "degraded"
        assert body["objectives"]["quality"]["burn_rates"]["300s"] > 1.0

    def test_failing_maps_to_503(self):
        clk, t = self._tracker(failing_burn=5.0)
        for _ in range(100):
            t.record_admission(False)
        code, body = t.healthz()
        assert code == 503 and body["status"] == "failing"

    def test_multiwindow_and_rule(self):
        """A burst that only the short window still sees must NOT degrade
        once the long window has diluted below threshold — and vice versa:
        stale long-window badness with a clean short window stays ready."""
        clk, t = self._tracker(degraded_burn=1.0, failing_burn=1000.0)
        t.record_quality(0, 200)      # total miss in slot 0
        clk[0] = 600.0                # outside 300s, inside 3600s
        t.record_quality(1000, 1000)  # clean current slot
        rates = t.burn_rates()["quality"]
        # long window: 200 bad / 1200 -> burn ~1.67; short window: clean
        assert rates["300s"] < 1.0 <= rates["3600s"]
        assert t.status() == "ready"

    def test_burn_gauges_published(self):
        before = obs.to_json()
        clk, t = self._tracker()
        t.record_quality(0, 10)
        t.status()
        d = obs.delta(before, obs.to_json())
        key = 'raft_tpu_slo_burn_rate{objective="quality",window="300s"}'
        assert d.get(key, 0) == pytest.approx(10.0)
        assert d.get('raft_tpu_slo_status{name="t"}', 0) == 2.0  # failing
        assert d.get('raft_tpu_slo_events_total'
                     '{objective="quality",outcome="bad"}') == 10.0

    def test_policy_validation(self):
        with pytest.raises(Exception, match="multiple"):
            slo.SLOTracker(slo.SLOPolicy(slot_s=30.0, windows_s=(100.0,)))
        with pytest.raises(Exception, match="targets"):
            slo.SLOTracker(slo.SLOPolicy(availability_target=1.5))


# ---------------------------------------------------------------------------
# request log: rid threading, spans, exemplars
# ---------------------------------------------------------------------------


@pytest.mark.quality
class TestRequestLog:
    def test_spans_thread_through_service_and_stream(self, rng):
        clk = [0.0]
        rl = requestlog.RequestLog(capacity=32, clock=lambda: clk[0])
        x, m, svc = _small_stack(rng, request_log=rl)
        fut = svc.submit("q", x[:2], 5)
        while svc.pump(force=True):
            pass
        fut.result()
        entries = rl.recent()
        assert len(entries) == 1
        e = entries[0]
        assert e["rid"].startswith("req-") and e["outcome"] == "ok"
        assert e["stream"] == "q.k5" and e["rows"] == 2 and e["bucket"] == 2
        for span in ("queue", "flush", "serve/lease", "serve/search",
                     "stream/sealed", "stream/delta", "stream/merge"):
            assert span in e["spans_ms"], e["spans_ms"]
        # the flush leased version 1 of the epoch-0 mutable
        assert e["notes"]["version"] == 1
        assert e["notes"]["stream_epoch"] == 0
        assert e["total_ms"] >= e["spans_ms"]["flush"]

    def test_expired_requests_are_traced_and_burn_latency(self, rng):
        clk = [0.0]
        rl = requestlog.RequestLog(clock=lambda: clk[0])
        tracker = slo.SLOTracker(clock=lambda: clk[0])
        x, m, svc = _small_stack(rng, request_log=rl, slo=tracker,
                                 clock=lambda: clk[0])
        svc.submit("q", x[:1], 5, timeout_s=0.5)
        clk[0] = 1.0  # expire in queue
        svc.pump(force=True)
        e = rl.recent()[-1]
        assert e["outcome"] == "expired"
        assert e["spans_ms"]["queue"] == pytest.approx(1000.0)
        assert "flush" not in e["spans_ms"]
        # an expired request is a latency-bad SLO outcome: a saturated
        # service shedding at the deadline must burn budget, not stay
        # 'ready' over the surviving minority
        assert tracker.burn_rate("latency", 300.0) > 0

    def test_ring_slowest_and_exemplars(self):
        clk = [0.0]
        rl = requestlog.RequestLog(capacity=4, clock=lambda: clk[0])
        for i, total in enumerate((0.002, 0.030, 0.004, 0.0007, 0.009)):
            rid = rl.begin("s", 1)
            rl.complete(rid, stream="s", rows=1, bucket=1,
                        spans={"queue": total / 2, "flush": total / 2})
        assert len(rl.recent()) == 4  # capacity-bounded: the oldest fell off
        slowest = rl.slowest(2)
        assert slowest[0]["total_ms"] == pytest.approx(30.0)
        ex = rl.exemplars()
        # 0.03s lands in the le=0.05 latency bucket; the exemplar links it
        assert ex["0.05"]["rid"] == slowest[0]["rid"]
        payload = rl.to_json()
        assert set(payload) == {"capacity", "in_flight", "recent", "slowest",
                                "exemplars"}
        assert payload["in_flight"] == []  # everything begun was completed

    def test_in_flight_visible_until_completed(self):
        clk = [0.0]
        rl = requestlog.RequestLog(capacity=4, in_flight_capacity=4,
                                   clock=lambda: clk[0])
        rid = rl.begin("s", 2)
        inf = rl.in_flight()
        assert inf == [{"rid": rid, "stream": "s", "rows": 2,
                        "admitted_at": 0.0}]
        rl.complete(rid, stream="s", rows=2, spans={"queue": 0.001})
        assert rl.in_flight() == []
        # never-completed rids are evicted past in_flight_capacity (a cap
        # sized to the serve queue bound, so only leaked entries go)
        stale = rl.begin("s", 1)
        for _ in range(4):
            rl.begin("s", 1)
        assert stale not in {e["rid"] for e in rl.in_flight()}
        assert len(rl.in_flight()) == 4

    def test_none_rid_is_noop(self):
        rl = requestlog.RequestLog()
        rl.complete(None, stream="s", rows=1, spans={"queue": 1.0})
        assert rl.recent() == []


# ---------------------------------------------------------------------------
# HTTP endpoints: explicit routing (ISSUE 8 satellite)
# ---------------------------------------------------------------------------


def _get(url):
    try:
        with urllib.request.urlopen(url, timeout=5) as resp:
            return resp.status, resp.headers.get("Content-Type", ""), \
                resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.headers.get("Content-Type", ""), e.read().decode()


@pytest.mark.quality
class TestHttpRouting:
    def test_routes_and_404(self):
        clk = [0.0]
        tracker = slo.SLOTracker(clock=lambda: clk[0])
        rl = requestlog.RequestLog(clock=lambda: clk[0])
        rid = rl.begin("s", 1)
        rl.complete(rid, stream="s", rows=1, spans={"queue": 0.001,
                                                    "flush": 0.002})
        obs.counter("raft_tpu_items_total", "rows").inc(1, op="route-test")
        with obs.MetricsExporter(port=0, slo=tracker, request_log=rl) as exp:
            base = f"http://127.0.0.1:{exp.port}"
            code, ctype, body = _get(base + "/metrics")
            assert code == 200 and ctype.startswith("text/plain")
            assert 'raft_tpu_items_total{op="route-test"}' in body
            code, ctype, body = _get(base + "/healthz")
            assert code == 200 and ctype.startswith("application/json")
            assert json.loads(body)["status"] == "ready"
            code, _, body = _get(base + "/debug/requests")
            assert code == 200
            payload = json.loads(body)
            assert payload["recent"][0]["rid"] == rid
            assert payload["exemplars"]
            # the satellite: unknown paths 404 loudly — a scrape-config
            # typo must not silently receive the exposition format
            for bad in ("/", "/metrcs", "/metrics/extra", "/debug"):
                code, _, body = _get(base + bad)
                assert code == 404, bad
                assert "/metrics, /healthz, /debug/requests" in body

    def test_healthz_503_on_failing_and_no_sources(self):
        clk = [0.0]
        tracker = slo.SLOTracker(
            slo.SLOPolicy(failing_burn=5.0), clock=lambda: clk[0])
        for _ in range(50):
            tracker.record_admission(False)
        with obs.MetricsExporter(port=0, slo=tracker) as exp:
            base = f"http://127.0.0.1:{exp.port}"
            code, _, body = _get(base + "/healthz")
            assert code == 503 and json.loads(body)["status"] == "failing"
            code, _, _ = _get(base + "/debug/requests")
            assert code == 404  # no request log attached
        with obs.MetricsExporter(port=0) as exp:
            code, _, body = _get(f"http://127.0.0.1:{exp.port}/healthz")
            assert code == 200
            assert json.loads(body)["note"] == "no SLO tracker attached"


# ---------------------------------------------------------------------------
# metrics satellites: ratio buckets + to_json bucket flattening
# ---------------------------------------------------------------------------


@pytest.mark.quality
class TestMetricsSatellites:
    def test_both_bucket_families_and_quantiles(self):
        """The satellite's unit test: a latency-ladder histogram and a 0-1
        ratio histogram side by side, with quantile() correct on each."""
        reg = obs.Registry()
        lat = reg.histogram("lat_seconds")  # DEFAULT_BUCKETS
        ratio = reg.histogram("recall_ratio", buckets=obs.RATIO_BUCKETS)
        for v in (0.003, 0.004, 0.020):
            lat.observe(v, op="x")
        for v in (0.93, 0.97, 0.97, 0.50):
            ratio.observe(v, op="x")
        # latency median lands in (0.0025, 0.005]
        assert 0.0025 <= lat.quantile(0.5, op="x") <= 0.005
        # ratio median lands in (0.9, 0.95] — a latency ladder would have
        # dumped all four into (0.25, 0.5]/(0.5, 1.0] and reported ~garbage
        assert 0.9 <= ratio.quantile(0.5, op="x") <= 0.95
        assert 0.95 <= ratio.quantile(0.9, op="x") <= 0.99
        assert obs.RATIO_BUCKETS[-1] == 1.0  # nothing above the unit range
        snap = reg.snapshot()["recall_ratio"]["series"][0]
        assert snap["buckets"]["1.0"] == 4 and snap["buckets"]["+Inf"] == 4

    def test_rebucketing_conflict_raises(self):
        reg = obs.Registry()
        reg.histogram("h", buckets=(0.5, 1.0))
        with pytest.raises(ValueError, match="buckets"):
            reg.histogram("h", buckets=obs.RATIO_BUCKETS)
        reg.histogram("h", buckets=(0.5, 1.0))  # same ladder: fine

    def test_to_json_flattens_buckets_with_labels(self):
        """The BENCH-artifact satellite: histogram series flatten with
        their label sets preserved — per-bucket keys carry the series
        labels PLUS le, and delta() subtracts them."""
        reg = obs.Registry()
        h = reg.histogram("r", buckets=(0.5, 1.0))
        h.observe(0.3, name="a", kind="x")
        h.observe(0.9, name="b", kind="x")
        j = reg.to_json()
        assert j['r_bucket{kind="x",le="0.5",name="a"}'] == 1
        assert j['r_bucket{kind="x",le="0.5",name="b"}'] == 0
        assert j['r_bucket{kind="x",le="1.0",name="b"}'] == 1
        assert j['r_bucket{kind="x",le="+Inf",name="a"}'] == 1
        assert j['r_sum{kind="x",name="a"}'] == pytest.approx(0.3)
        before = dict(j)
        h.observe(0.4, name="a", kind="x")
        d = obs.delta(before, reg.to_json())
        assert d['r_bucket{kind="x",le="0.5",name="a"}'] == 1
        assert d['r_count{kind="x",name="a"}'] == 1

    def test_math_helpers_stay_finite(self):
        # quantile on the ratio family's +Inf bucket reports the last
        # finite bound (1.0), never inf
        reg = obs.Registry()
        h = reg.histogram("r", buckets=obs.RATIO_BUCKETS)
        h.observe(1.0)
        assert math.isfinite(h.quantile(0.99))
