"""Lanczos eigensolver + spectral partition tests.

Mirrors the reference's SOLVERS_TEST / cpp/test/spectral suites (SURVEY.md §4):
eigenpairs validated against scipy/numpy dense references, partitions validated
as exact recovery of planted blocks plus cost/modularity sanity.
"""

import numpy as np
import pytest
import scipy.sparse as sps

from raft_tpu import sparse, spectral
from raft_tpu.solver import compute_largest_eigenvectors, eigsh


def _two_block_graph(rng, n_per=24, p_in=0.85, p_out=0.02):
    n = 2 * n_per
    dense = (rng.random((n, n)) < p_out).astype(np.float32)
    dense[:n_per, :n_per] = (rng.random((n_per, n_per)) < p_in).astype(np.float32)
    dense[n_per:, n_per:] = (rng.random((n_per, n_per)) < p_in).astype(np.float32)
    dense = np.triu(dense, 1)
    dense = dense + dense.T
    # keep connected: ring backbone
    for i in range(n):
        dense[i, (i + 1) % n] = dense[(i + 1) % n, i] = max(dense[i, (i + 1) % n], 0.05)
    np.fill_diagonal(dense, 0.0)
    return dense


class TestLanczos:
    def test_smallest_dense_psd(self, rng):
        n = 60
        a = rng.standard_normal((n, n)).astype(np.float32)
        a = a @ a.T / n + np.diag(np.linspace(0.5, 5.0, n)).astype(np.float32)
        w, v, _ = eigsh(a, k=4, which="SA", tol=1e-8, max_iter=2000)
        ref = np.linalg.eigvalsh(a)[:4]
        np.testing.assert_allclose(np.asarray(w), ref, rtol=2e-3, atol=2e-3)
        # residual check ||A v - v w||
        res = a @ np.asarray(v) - np.asarray(v) * np.asarray(w)[None, :]
        assert np.linalg.norm(res, axis=0).max() < 5e-2

    def test_largest_matches_numpy(self, rng):
        n = 48
        a = rng.standard_normal((n, n)).astype(np.float32)
        a = (a + a.T) / 2
        w, v, _ = compute_largest_eigenvectors(a, k=3, tol=1e-8)
        ref = np.linalg.eigvalsh(a)[-3:]  # ascending, scipy-eigsh order
        np.testing.assert_allclose(np.asarray(w), ref, rtol=2e-3, atol=2e-3)

    def test_sparse_laplacian_smallest(self, rng):
        dense = _two_block_graph(rng, n_per=20)
        adj = sparse.from_scipy(sps.csr_matrix(dense), cap=int((dense > 0).sum()) + 8)
        lap = sparse.laplacian(adj)
        w, v, _ = eigsh(lap, k=3, which="SA", tol=1e-7, max_iter=3000)
        lap_dense = np.diag(dense.sum(1)) - dense
        ref = np.linalg.eigvalsh(lap_dense)[:3]
        np.testing.assert_allclose(np.asarray(w), ref, rtol=5e-3, atol=5e-3)
        # smallest eigenvalue of a Laplacian is 0 with constant eigenvector
        assert abs(float(w[0])) < 1e-3

    def test_callable_operator(self, rng):
        n = 32
        d = np.linspace(1.0, 10.0, n).astype(np.float32)
        w, _, _ = eigsh(lambda x: d * x, n=n, k=2, which="SA", tol=1e-8)
        np.testing.assert_allclose(np.asarray(w), d[:2], rtol=1e-3, atol=1e-3)


class TestPartition:
    def test_recovers_planted_blocks(self, rng):
        dense = _two_block_graph(rng)
        n = dense.shape[0]
        adj = sparse.from_scipy(sps.csr_matrix(dense), cap=int((dense > 0).sum()) + 8)
        out = spectral.partition(
            adj, n_clusters=2,
            eigen_cfg=spectral.EigenSolverConfig(n_eig_vecs=2, tol=1e-6),
        )
        labels = np.asarray(out.labels)
        truth = np.array([0] * (n // 2) + [1] * (n // 2))
        agree = max((labels == truth).mean(), (labels == 1 - truth).mean())
        assert agree > 0.95

    def test_analyze_partition(self, rng):
        dense = _two_block_graph(rng)
        n = dense.shape[0]
        adj = sparse.from_scipy(sps.csr_matrix(dense), cap=int((dense > 0).sum()) + 8)
        truth = np.array([0] * (n // 2) + [1] * (n // 2))
        edge_cut, cost = spectral.analyze_partition(adj, 2, truth)
        # cross-block edge weight, counted once
        expected_cut = dense[: n // 2, n // 2:].sum()
        np.testing.assert_allclose(float(edge_cut), expected_cut, rtol=1e-4)
        assert float(cost) > 0
        # random labels should cut strictly more
        rand_cut, _ = spectral.analyze_partition(adj, 2, rng.integers(0, 2, n))
        assert float(edge_cut) < float(rand_cut)

    def test_modularity_maximization(self, rng):
        dense = _two_block_graph(rng)
        n = dense.shape[0]
        adj = sparse.from_scipy(sps.csr_matrix(dense), cap=int((dense > 0).sum()) + 8)
        out = spectral.modularity_maximization(
            adj, n_clusters=2,
            eigen_cfg=spectral.EigenSolverConfig(n_eig_vecs=2, tol=1e-6),
        )
        labels = np.asarray(out.labels)
        truth = np.array([0] * (n // 2) + [1] * (n // 2))
        agree = max((labels == truth).mean(), (labels == 1 - truth).mean())
        assert agree > 0.9
        mod_found = float(spectral.analyze_modularity(adj, 2, labels))
        mod_rand = float(spectral.analyze_modularity(adj, 2, rng.integers(0, 2, n)))
        assert mod_found > mod_rand
        assert mod_found > 0.2

    def test_modularity_matches_networkx_formula(self, rng):
        dense = _two_block_graph(rng, n_per=12)
        n = dense.shape[0]
        adj = sparse.from_scipy(sps.csr_matrix(dense), cap=int((dense > 0).sum()) + 8)
        labels = rng.integers(0, 3, n)
        got = float(spectral.analyze_modularity(adj, 3, labels))
        # direct formula: sum_ij (A_ij - d_i d_j / 2m) [c_i == c_j] / 2m
        d = dense.sum(1)
        two_m = d.sum()
        same = labels[:, None] == labels[None, :]
        ref = ((dense - np.outer(d, d) / two_m) * same).sum() / two_m
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)
