"""Native host runtime tests (cpp/runtime.cpp via ctypes).

Analogue of the reference's runtime-library smoke coverage: binary IO
roundtrips (bench/ann dataset.h format), host refine vs numpy reference
(test/neighbors/refine.cu host path), merge_parts vs select over the
concatenation. Tests exercise the native path when a toolchain is present
and the numpy fallback otherwise.
"""

import numpy as np
import pytest

from raft_tpu import runtime


def test_bin_roundtrip(tmp_path, rng):
    x = rng.random((37, 9)).astype(np.float32)
    p = str(tmp_path / "data.fbin")
    runtime.write_bin(p, x)
    n, d = runtime.bin_info(p)
    assert (n, d) == (37, 9)
    back = runtime.load_bin(p)
    np.testing.assert_array_equal(back, x)
    chunk = runtime.read_bin_chunk(p, 10, 5)
    np.testing.assert_array_equal(chunk, x[10:15])


def test_bin_u8_and_dataset_stream(tmp_path, rng):
    x = (rng.random((64, 7)) * 255).astype(np.uint8)
    p = str(tmp_path / "data.u8bin")
    runtime.write_bin(p, x)
    ds = runtime.BinDataset(p)
    assert len(ds) == 64 and ds.dim == 7 and ds.dtype == np.uint8
    got = np.concatenate([c for _, c in ds.chunks(20)])
    np.testing.assert_array_equal(got, x)
    np.testing.assert_array_equal(ds[8:24], x[8:24])


def test_refine_host_l2(rng):
    n, d, m, k_in, k = 200, 12, 9, 20, 6
    x = rng.random((n, d)).astype(np.float32)
    q = rng.random((m, d)).astype(np.float32)
    cand = np.stack([rng.choice(n, k_in, replace=False) for _ in range(m)]).astype(np.int32)
    dists, idx = runtime.refine_host(x, q, cand, k)
    # reference: exact distances over candidates, ascending
    d2 = ((q[:, None, :].astype(np.float64) - x[cand]) ** 2).sum(-1)
    order = np.argsort(d2, axis=1)[:, :k]
    want_i = np.take_along_axis(cand, order, axis=1)
    want_d = np.take_along_axis(d2, order, axis=1)
    np.testing.assert_array_equal(idx, want_i)
    np.testing.assert_allclose(dists, want_d, rtol=1e-4, atol=1e-4)


def test_refine_host_invalid_ids(rng):
    n, d, m = 50, 4, 3
    x = rng.random((n, d)).astype(np.float32)
    q = rng.random((m, d)).astype(np.float32)
    cand = np.full((m, 5), -1, np.int32)
    cand[:, 0] = 7
    dists, idx = runtime.refine_host(x, q, cand, 3)
    assert (idx[:, 0] == 7).all()
    assert (idx[:, 1:] == -1).all()
    assert np.isinf(dists[:, 1:]).all()


def test_refine_host_inner_product(rng):
    n, d, m, k = 100, 8, 5, 4
    x = rng.random((n, d)).astype(np.float32)
    q = rng.random((m, d)).astype(np.float32)
    cand = np.stack([rng.choice(n, 10, replace=False) for _ in range(m)]).astype(np.int32)
    dists, idx = runtime.refine_host(x, q, cand, k, metric="inner_product")
    ip = np.einsum("md,mkd->mk", q, x[cand])
    order = np.argsort(-ip, axis=1)[:, :k]
    np.testing.assert_array_equal(idx, np.take_along_axis(cand, order, axis=1))
    np.testing.assert_allclose(dists, np.take_along_axis(ip, order, axis=1), rtol=1e-4)


def test_merge_parts_host(rng):
    n_parts, m, k = 4, 7, 5
    d = rng.random((n_parts, m, k)).astype(np.float32)
    ids = rng.integers(0, 10_000, (n_parts, m, k)).astype(np.int32)
    out_d, out_i = runtime.merge_parts_host(d, ids, k)
    flat_d = np.moveaxis(d, 0, 1).reshape(m, -1)
    flat_i = np.moveaxis(ids, 0, 1).reshape(m, -1)
    order = np.argsort(flat_d, axis=1)[:, :k]
    np.testing.assert_allclose(out_d, np.take_along_axis(flat_d, order, axis=1))
    # ids may differ on exact ties; distances are the contract
    assert out_i.shape == (m, k)


def test_native_available_or_fallback():
    # informational: record which path the suite exercised
    assert runtime.available() in (True, False)


def test_make_fbin_roundtrip(tmp_path):
    """bench/ann/make_fbin.py writes chunked big-ANN files the native loader
    reads back intact (the no-network stand-in for downloading SIFT-1M)."""
    import subprocess
    import sys
    from pathlib import Path

    repo = Path(__file__).resolve().parents[1]
    r = subprocess.run(
        [sys.executable, str(repo / "bench/ann/make_fbin.py"), "--out",
         str(tmp_path), "--n", "300000", "--n-queries", "50", "--dim", "16",
         "--clusters", "10"],
        capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr

    from raft_tpu.runtime import bin_info, load_bin, read_bin_chunk

    base = str(tmp_path / "base-300000x16.fbin")
    assert bin_info(base) == (300000, 16)
    rows = read_bin_chunk(base, 299_990, 10)
    assert rows.shape == (10, 16)
    q = load_bin(str(tmp_path / "query-50x16.fbin"))
    assert q.shape == (50, 16)
    # chunk boundary continuity: rows on either side of the 200k chunk edge
    a = read_bin_chunk(base, 199_999, 2)
    assert np.isfinite(a).all() and a.std() > 0
