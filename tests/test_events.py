"""Unified operations event plane (ISSUE 17, tier-1 ``events`` marker).

The journal's contracts, each deterministic — injected clocks, threaded
emitters without wall sleeps, faults via :mod:`raft_tpu.testing.faults`:

- strictly increasing sequence numbers under concurrent emitters;
- bounded-ring eviction with eviction-proof cumulative per-kind counts;
- ``since_seq`` pagination (exclusive cursor — no gaps, no repeats);
- subscriber taps (in-order delivery, unsubscribe, a raising tap never
  breaks the emitter);
- the durable JSONL sink (atomic rotation, torn-tail tolerant reload);
- the disabled fast path (one flag check: the injected clock is never
  read, nothing lands anywhere);
- the drift → pressure-spill → fence → reshard-advice causal chain read
  back as one ordered timeline, and the same filters over HTTP at
  ``/debug/events``;
- the incident flight recorder (SLO ``failing`` → complete bundle,
  rate-limited on the journal clock);
- per-call-site log/metric/journal consistency: one emit carries all
  three, so they cannot disagree on re-arm paths.
"""

import json
import logging
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from raft_tpu import obs
from raft_tpu.obs import events, metrics, requestlog, slo

pytestmark = pytest.mark.events


class FakeClock:
    def __init__(self):
        self.t = 0.0
        self.reads = 0

    def __call__(self):
        self.reads += 1
        return self.t

    def advance(self, dt):
        self.t += dt


@pytest.fixture(autouse=True)
def _fresh_journal():
    """Every test runs against its OWN process journal (small, injected
    clock available via reconfigure) and leaves obs enabled."""
    obs.enable()
    events.configure(capacity=2048)
    yield
    events.detach_sink()
    events.disarm_flight_recorder()
    events.configure(capacity=2048)
    obs.enable()


def _get(url):
    try:
        with urllib.request.urlopen(url, timeout=5) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


# ---------------------------------------------------------------------------
# journal core
# ---------------------------------------------------------------------------


class TestJournalCore:
    def test_emit_shape_metric_and_request_id(self):
        before = obs.to_json()
        ev = events.emit("tier_spill", subject=("tier", "s", 3, 7),
                         evidence={"reason": "pressure"},
                         request_id="req-00000042")
        assert ev["kind"] == "tier_spill"
        assert ev["severity"] == "info"  # KINDS default
        assert (ev["component"], ev["name"], ev["shard"], ev["epoch"]) \
            == ("tier", "s", 3, 7)
        assert ev["request_id"] == "req-00000042"
        assert ev["seq"] == events.last_seq()
        d = obs.delta(before, obs.to_json())
        assert d.get('raft_tpu_events_total'
                     '{kind="tier_spill",severity="info"}') == 1
        # severity override lands in both the event and the metric label
        ev2 = events.emit("tier_spill", severity="warning",
                          subject=("tier", "s"))
        assert ev2["severity"] == "warning" and ev2["seq"] == ev["seq"] + 1

    def test_unknown_kind_and_severity_rejected(self):
        with pytest.raises(ValueError, match="unknown event kind"):
            events.emit("not_a_kind", subject=("x", "y"))
        with pytest.raises(ValueError, match="unknown severity"):
            events.emit("tier_spill", severity="fatal")

    def test_concurrent_emitters_strictly_increasing_seq(self):
        j = events.EventJournal(capacity=4096)
        n_threads, per = 8, 50
        start = threading.Barrier(n_threads)

        def worker(i):
            start.wait()
            for _ in range(per):
                j.emit("replica_probe", subject=("replica", f"t{i}", i))

        ts = [threading.Thread(target=worker, args=(i,))
              for i in range(n_threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        evs = j.query()
        seqs = [e["seq"] for e in evs]
        assert len(seqs) == n_threads * per
        assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
        assert seqs[-1] == j.last_seq() == n_threads * per
        assert j.counts_by_kind() == {"replica_probe": n_threads * per}

    def test_ring_eviction_keeps_cumulative_counts(self):
        j = events.EventJournal(capacity=8)
        for i in range(20):
            j.emit("wal_truncated", subject=("wal", "w"),
                   evidence={"i": i})
        kept = j.tail(100)
        assert len(kept) == 8  # ring bound holds
        assert [e["seq"] for e in kept] == list(range(13, 21))
        assert j.last_seq() == 20
        # attribution survives eviction: the bench field reads this
        assert j.counts_by_kind() == {"wal_truncated": 20}

    def test_since_seq_pagination_no_gaps_no_repeats(self):
        j = events.EventJournal(capacity=64)
        for i in range(30):
            j.emit("serve_published", subject=("serve", "s", None, i))
        seen, cursor = [], 0
        while True:
            page = j.query(since_seq=cursor, limit=7)
            if not page:
                break
            seen.extend(e["seq"] for e in page)
            cursor = page[-1]["seq"]  # the exclusive cursor contract
        assert seen == list(range(1, 31))

    def test_query_filters(self):
        j = events.EventJournal(capacity=64)
        j.emit("tier_spill", subject=("tier", "a"))
        j.emit("tier_spill", severity="warning", subject=("tier", "b"))
        j.emit("replica_fenced", subject=("replica", "a", 0))
        assert [e["name"] for e in j.query(kind="tier_spill")] == ["a", "b"]
        assert [e["kind"] for e in j.query(component="tier")] \
            == ["tier_spill", "tier_spill"]
        assert [e["kind"] for e in j.query(name="a")] \
            == ["tier_spill", "replica_fenced"]
        # seq 3 rides along: replica_fenced defaults to warning in KINDS
        assert [e["seq"] for e in j.query(severity="warning")] == [2, 3]

    def test_taps_in_order_unsubscribe_and_raising_tap(self):
        j = events.EventJournal(capacity=64)
        seen: list = []
        j.subscribe(seen.append)

        def bad(ev):
            raise RuntimeError("tap must never break the emitter")

        j.subscribe(bad)
        for i in range(5):
            assert j.emit("replica_probe", subject=("replica", "g", i)) \
                is not None  # the raising tap was swallowed
        assert [e["seq"] for e in seen] == [1, 2, 3, 4, 5]
        j.unsubscribe(seen.append)
        j.emit("replica_probe", subject=("replica", "g", 9))
        assert len(seen) == 5  # unsubscribed: no more deliveries

    def test_transition_dedup_and_standing_payload(self):
        j = events.EventJournal()
        k = ("adv", 0)
        assert j.transition(k, None) is False  # vacuous first clear
        assert j.transition(k, "split:4", {"action": "split"}) is True
        assert j.transition_payload(k) == {"action": "split"}
        assert j.transition(k, "split:4", {"action": "split"}) is False
        assert j.transition(k, None) is True  # clearing IS a transition
        assert j.transition_payload(k) is None
        assert j.transition(k, "merge:2", {"action": "merge"}) is True
        # dedup state is NOT obs-gated: standing advisories answer
        # correctly even while the observable surface is off
        obs.disable()
        try:
            assert j.transition(k, "merge:2") is False
            assert j.transition_payload(k) == {"action": "merge"}
        finally:
            obs.enable()

    def test_clear_keeps_seq_monotonic(self):
        j = events.EventJournal()
        j.emit("wal_recovered", subject=("wal", "w"))
        j.clear()
        assert j.tail(10) == [] and j.counts_by_kind() == {}
        ev = j.emit("wal_recovered", subject=("wal", "w"))
        assert ev["seq"] == 2  # a since_seq cursor never sees a restart


# ---------------------------------------------------------------------------
# disabled fast path
# ---------------------------------------------------------------------------


class TestDisabledPath:
    def test_disabled_emit_is_one_flag_check(self):
        clk = FakeClock()
        events.configure(capacity=64, clock=clk)
        events.emit("tier_promote", subject=("tier", "t"))
        reads_enabled = clk.reads
        assert reads_enabled >= 1 and events.last_seq() == 1
        obs.disable()
        try:
            before = obs.to_json()
            assert events.emit("tier_promote", subject=("tier", "t")) is None
            assert clk.reads == reads_enabled  # clock never read
            assert events.last_seq() == 1      # nothing appended
            assert events.counts_by_kind() == {"tier_promote": 1}
            assert obs.delta(before, obs.to_json()) == {}
        finally:
            obs.enable()
        # re-enable: sequence resumes where it left off
        assert events.emit("tier_promote",
                           subject=("tier", "t"))["seq"] == 2

    def test_disabled_emit_skips_taps_and_sink(self, tmp_path):
        p = str(tmp_path / "sink.jsonl")
        events.attach_sink(p)
        seen: list = []
        events.subscribe(seen.append)
        obs.disable()
        try:
            events.emit("tier_spill", subject=("tier", "t"))
        finally:
            obs.enable()
        events.detach_sink()
        assert seen == [] and events.load_jsonl(p) == []


# ---------------------------------------------------------------------------
# durable JSONL sink
# ---------------------------------------------------------------------------


class TestSink:
    def test_sink_rotation_and_reload(self, tmp_path):
        p = str(tmp_path / "events.jsonl")
        j = events.EventJournal(capacity=64)
        j.attach_sink(p, rotate_bytes=600)
        for i in range(12):
            j.emit("serve_retired", subject=("serve", "s", None, i))
        j.detach_sink()
        assert (tmp_path / "events.jsonl.1").exists(), \
            "the sink must have rotated at the size bound"
        old = events.load_jsonl(p + ".1")
        new = events.load_jsonl(p)
        assert old  # at least one rotated generation landed
        seqs = [e["seq"] for e in old + new]
        # one rotated generation + the live file hold a contiguous,
        # gapless suffix ending at the newest event
        assert len(seqs) >= 4 and seqs == list(range(seqs[0], 13))
        assert all(e["kind"] == "serve_retired" for e in old + new)

    def test_torn_tail_reload(self, tmp_path):
        p = str(tmp_path / "events.jsonl")
        j = events.EventJournal(capacity=64)
        j.attach_sink(p)
        for i in range(4):
            j.emit("wal_truncated", subject=("wal", "w"),
                   evidence={"i": i})
        j.detach_sink()
        with open(p, "ab") as f:
            f.write(b'{"seq": 99, "kind": "wal_trunc')  # crash mid-append
        back = events.load_jsonl(p)
        assert [e["evidence"]["i"] for e in back] == [0, 1, 2, 3]
        assert events.load_jsonl(str(tmp_path / "missing.jsonl")) == []

    def test_sink_survives_write_failure(self, tmp_path):
        p = str(tmp_path / "events.jsonl")
        j = events.EventJournal(capacity=64)
        j.attach_sink(p)
        j.emit("wal_truncated", subject=("wal", "w"))
        j._sink_f.close()  # simulate the descriptor dying (EIO/ENOSPC)
        ev = j.emit("wal_truncated", subject=("wal", "w"))
        assert ev is not None and ev["seq"] == 2  # emitter survives
        assert j._sink_f is None  # sink detached itself
        assert len(events.load_jsonl(p)) == 1


# ---------------------------------------------------------------------------
# the causal chain: drift -> pressure spill -> fence -> reshard advice
# ---------------------------------------------------------------------------


def _heavytail_rows():
    from raft_tpu.tune.reference import _clustered

    x, _ = _clustered(2000, 32, 8, 64, seed=29, heavytail=True)
    return np.asarray(x)


class TestCausalChain:
    def test_injected_scenario_reads_as_one_ordered_timeline(self, rng):
        """The acceptance scenario: four independent subsystems misbehave
        in a known order; the journal replays them as ONE causally
        ordered timeline — strictly increasing seq, each event
        attributed to its subject."""
        import jax.numpy as jnp

        from raft_tpu import stream
        from raft_tpu.neighbors import brute_force
        from raft_tpu.obs import quality
        from raft_tpu.testing import faults
        from raft_tpu.tune import shape_family

        clk = FakeClock()
        data = rng.standard_normal((256, 16)).astype(np.float32)
        queries = rng.standard_normal((4, 16)).astype(np.float32)

        # 1) family drift fires retune_advised
        det = quality.DriftDetector(shape_family(2000, 32, "bal"),
                                    name="evt-drift", min_rows=128)
        det.offer_rows(_heavytail_rows()[:512])
        assert det.check()["drifted"]

        # 2) a budget squeeze spills the tier mirror
        ts = stream.TieredStore(data, name="evt-tier")
        assert ts.promote(force=True)
        ts.spill(reason="pressure")

        # 3) an injected replica fault fences a twin
        g = stream.ReplicatedShard(
            brute_force.BruteForce().build(jnp.asarray(data)),
            n_replicas=2, delta_capacity=64,
            policy=stream.FencingPolicy(max_consecutive=1, backoff_s=5.0),
            clock=clk, name="evt-g")
        with faults.scope():
            # whichever replica the pick lands on dies once: the failover
            # serves the query and the breaker fences the struck twin
            faults.inject("replica/search", exc=faults.FaultError("dead"),
                          times=1)
            g.search(queries, 5)

        # 4) the compactor's watermark advises a split
        sm = stream.ShardedMutableIndex(
            data, n_shards=2, delta_capacity=32, clock=clk,
            name="evt-mesh",
            build=lambda r: brute_force.BruteForce().build(jnp.asarray(r)))
        comp = stream.Compactor(
            sm, policy=stream.CompactionPolicy(
                delta_fill=None, tombstone_ratio=None,
                reshard_rows_per_shard=100),
            clock=clk)
        comp.run_once()
        assert comp.last_advice is not None

        timeline = events.query()
        seqs = [e["seq"] for e in timeline]
        assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
        by_kind = {e["kind"]: e for e in timeline}
        chain = ["retune_advised", "tier_spill", "replica_fenced",
                 "reshard_advised"]
        assert all(k in by_kind for k in chain), sorted(by_kind)
        assert [by_kind[k]["seq"] for k in chain] \
            == sorted(by_kind[k]["seq"] for k in chain), \
            "journal order must match the injection order"
        # each event is attributed to its subject
        drift = by_kind["retune_advised"]
        assert (drift["component"], drift["name"]) == ("quality",
                                                       "evt-drift")
        spill = by_kind["tier_spill"]
        assert (spill["component"], spill["name"]) == ("tier", "evt-tier")
        assert spill["severity"] == "warning"  # pressure escalates
        assert spill["evidence"]["reason"] == "pressure"
        fence = by_kind["replica_fenced"]
        assert (fence["component"], fence["name"]) == ("replica", "evt-g")
        assert fence["shard"] in (0, 1)
        assert "FaultError" in fence["evidence"]["error"]
        adv = by_kind["reshard_advised"]
        assert (adv["component"], adv["name"]) == ("compactor", "evt-mesh")
        assert adv["evidence"]["action"] == "split"
        # the same chain, filtered server-side over HTTP
        with obs.MetricsExporter(port=0) as exp:
            base = f"http://127.0.0.1:{exp.port}"
            code, body = _get(base + "/debug/events"
                              f"?since_seq={drift['seq']}")
            assert code == 200
            payload = json.loads(body)
            assert [e["kind"] for e in payload["events"]
                    if e["kind"] in chain] == chain[1:]
            code, body = _get(base + "/debug/events?component=replica"
                              "&severity=warning")
            assert code == 200
            got = json.loads(body)["events"]
            assert got and all(e["component"] == "replica"
                               and e["severity"] == "warning" for e in got)


# ---------------------------------------------------------------------------
# /debug/events HTTP contract
# ---------------------------------------------------------------------------


class TestHttpEndpoint:
    def test_filters_pagination_and_404_list(self):
        for i in range(5):
            events.emit("serve_published", subject=("serve", "svc", None, i))
        events.emit("budget_refusal", subject=("mem", "site"))
        with obs.MetricsExporter(port=0) as exp:
            base = f"http://127.0.0.1:{exp.port}"
            code, body = _get(base + "/debug/events")
            assert code == 200
            payload = json.loads(body)
            assert payload["last_seq"] == 6
            assert payload["counts_by_kind"] == {"serve_published": 5,
                                                 "budget_refusal": 1}
            code, body = _get(base + "/debug/events?kind=serve_published"
                              "&since_seq=2&limit=2")
            evs = json.loads(body)["events"]
            assert [e["seq"] for e in evs] == [3, 4]
            code, body = _get(base + "/debug/events?severity=error")
            assert [e["kind"] for e in json.loads(body)["events"]] \
                == ["budget_refusal"]
            code, body = _get(base + "/debug/events?since_seq=oops")
            assert code == 400
            code, body = _get(base + "/nope")
            assert code == 404 and "/debug/events" in body


# ---------------------------------------------------------------------------
# incident flight recorder
# ---------------------------------------------------------------------------


class TestFlightRecorder:
    def test_slo_failing_writes_complete_bundle(self, tmp_path):
        clk = FakeClock()
        events.configure(capacity=256, clock=clk)
        rl = requestlog.RequestLog(clock=clk)
        rid = rl.begin("s", 1)
        rl.complete(rid, stream="s", rows=1,
                    spans={"queue": 0.001, "flush": 0.002})
        events.arm_flight_recorder(str(tmp_path), request_log=rl,
                                   min_interval_s=300.0, window=4)
        for i in range(6):  # context the bundle window should carry
            events.emit("replica_probe", subject=("replica", "g", i % 2))
        tracker = slo.SLOTracker(slo.SLOPolicy(failing_burn=5.0),
                                 name="evt-slo", clock=clk)
        for _ in range(50):
            tracker.record_admission(False)
        assert tracker.status() == "failing"  # transition -> auto bundle
        bundles = sorted(p for p in tmp_path.iterdir() if p.is_dir())
        assert len(bundles) == 1
        b = bundles[0]
        assert b.name.endswith("-slo_failing")
        for fname in ("events.json", "mem.json", "requests.json",
                      "metrics.json", "meta.json"):
            assert (b / fname).exists(), fname
        window = json.loads((b / "events.json").read_text())
        assert len(window) == 4  # the armed window bound
        assert window[-1]["kind"] == "slo_verdict"
        assert window[-1]["evidence"]["status"] == "failing"
        reqs = json.loads((b / "requests.json").read_text())
        assert reqs["recent"][0]["rid"] == rid
        meta = json.loads((b / "meta.json").read_text())
        assert meta["reason"] == "slo_failing"
        # the recorder leaves its breadcrumb in the journal
        crumbs = events.query(kind="flight_recorder")
        assert len(crumbs) == 1
        assert crumbs[0]["evidence"]["dir"] == str(b)

    def test_rate_limit_and_explicit_snapshot(self, tmp_path):
        clk = FakeClock()
        events.configure(capacity=64, clock=clk)
        events.arm_flight_recorder(str(tmp_path), min_interval_s=300.0)
        events.emit("wal_recovered", subject=("wal", "w"))
        assert events.snapshot("first", force=False) is not None
        # inside the interval: the auto path (force=False) is suppressed
        clk.advance(10.0)
        assert events.snapshot("second", force=False) is None
        # the explicit operator trigger bypasses the limit
        d = events.snapshot("manual")
        assert d is not None and d.endswith("-manual")
        # past the interval the auto path fires again
        clk.advance(400.0)
        assert events.snapshot("third", force=False) is not None
        assert len([p for p in tmp_path.iterdir() if p.is_dir()]) == 3

    def test_snapshot_without_recorder_armed(self, tmp_path):
        import os

        assert events.snapshot("nowhere") is None  # no dir: skipped
        d = events.snapshot("adhoc", dir_=str(tmp_path))
        assert d is not None and d.endswith("-adhoc")
        assert os.path.exists(os.path.join(d, "events.json"))


# ---------------------------------------------------------------------------
# call-site consistency: one emit = log + metric + journal (satellite 2)
# ---------------------------------------------------------------------------


class TestCallSiteConsistency:
    def test_drift_site_log_metric_journal_agree(self, caplog):
        from raft_tpu.obs import quality
        from raft_tpu.tune import shape_family

        before = obs.to_json()
        det = quality.DriftDetector(shape_family(2000, 32, "bal"),
                                    name="evt-agree", min_rows=128)
        det.offer_rows(_heavytail_rows()[:512])
        with caplog.at_level(logging.WARNING, logger="raft_tpu"):
            det.check()
            det.check()  # standing drift: no re-emit anywhere
        warns = [r for r in caplog.records
                 if "family drift on 'evt-agree'" in r.getMessage()]
        journal = [e for e in events.query(kind="retune_advised")
                   if e["name"] == "evt-agree"]
        d = obs.delta(before, obs.to_json())
        counted = d.get(
            'raft_tpu_quality_retune_advised_total{name="evt-agree"}', 0)
        assert len(warns) == len(journal) == counted == 1, (
            "the WARNING, the counter and the journal entry must move "
            f"together: log={len(warns)} journal={len(journal)} "
            f"metric={counted}")
        # the legacy view is the journal, reshaped
        assert det.events[0]["event"] == "retune_advised"
        assert det.events[0]["auto_apply"] is False
        assert journal[0]["evidence"]["observed"].endswith("-skew")

    def test_compactor_site_rearm_paths_agree(self, caplog, rng):
        import jax.numpy as jnp

        from raft_tpu import stream
        from raft_tpu.neighbors import brute_force

        data = rng.standard_normal((256, 16)).astype(np.float32)
        clk = FakeClock()
        sm = stream.ShardedMutableIndex(
            data, n_shards=2, delta_capacity=32, clock=clk, name="evt-adv",
            build=lambda r: brute_force.BruteForce().build(jnp.asarray(r)))
        comp = stream.Compactor(
            sm, policy=stream.CompactionPolicy(
                delta_fill=None, tombstone_ratio=None,
                reshard_rows_per_shard=100),
            clock=clk)
        before = obs.to_json()
        with caplog.at_level(logging.WARNING, logger="raft_tpu"):
            comp.run_once()
            comp.run_once()  # standing advice: no re-emit anywhere
        assert comp.last_advice["action"] == "split"  # journal-backed view
        warns = [r for r in caplog.records
                 if "reshard advised" in r.getMessage()]
        journal = events.query(kind="reshard_advised", name="evt-adv")
        counted = obs.delta(before, obs.to_json()).get(
            'raft_tpu_reshard_advised_total{action="split",name="evt-adv"}',
            0)
        assert len(warns) == len(journal) == counted == 1
        # acting on the advice clears it: the clear is itself journaled
        sm.reshard(4)
        comp.run_once()
        assert comp.last_advice is None
        cleared = events.query(kind="reshard_advice_cleared",
                               name="evt-adv")
        assert len(cleared) == 1
        assert cleared[0]["seq"] > journal[0]["seq"]
        # fold lifecycle rides the same journal
        kinds = {e["kind"] for e in events.query(component="compactor")}
        assert {"reshard_advised", "reshard_advice_cleared"} <= kinds

    def test_mem_refusal_site_metric_and_journal_agree(self):
        from raft_tpu.core import Resources
        from raft_tpu.obs import mem as obs_mem
        from raft_tpu.serve.errors import MemoryBudgetError

        class Ballast:  # plain object() cannot carry a weakref
            pass

        ballast = Ballast()
        tok = obs_mem.account("test/evt", name="ballast", owner=ballast,
                              device_bytes=1 << 20)
        try:
            before = obs.to_json()
            res = Resources(memory_budget_bytes=1)
            with pytest.raises(MemoryBudgetError):
                obs_mem.gate(res, 1 << 20, site="evt-site")
            journal = events.query(kind="budget_refusal", name="evt-site")
            counted = obs.delta(before, obs.to_json()).get(
                'raft_tpu_mem_budget_refusals_total{site="evt-site"}', 0)
            assert len(journal) == counted == 1
            assert journal[0]["evidence"]["need_bytes"] == 1 << 20
            assert journal[0]["severity"] == "error"
        finally:
            obs_mem.retire(tok)
