"""Linalg tests (reference analogue: cpp/test/linalg/*, LINALG_TEST)."""

import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu import linalg


class TestBlas:
    def test_gemm(self, rng):
        a = rng.random((5, 7)).astype(np.float32)
        b = rng.random((7, 3)).astype(np.float32)
        c = rng.random((5, 3)).astype(np.float32)
        out = np.asarray(linalg.gemm(a, b, c, alpha=2.0, beta=0.5))
        np.testing.assert_allclose(out, 2 * a @ b + 0.5 * c, rtol=1e-5)

    def test_gemm_transpose(self, rng):
        a = rng.random((7, 5)).astype(np.float32)
        b = rng.random((3, 7)).astype(np.float32)
        out = np.asarray(linalg.gemm(a, b, trans_a=True, trans_b=True))
        np.testing.assert_allclose(out, a.T @ b.T, rtol=1e-5)

    def test_gemv(self, rng):
        a = rng.random((4, 6)).astype(np.float32)
        x = rng.random(6).astype(np.float32)
        np.testing.assert_allclose(np.asarray(linalg.gemv(a, x)), a @ x, rtol=1e-5)

    def test_axpy_dot(self, rng):
        x = rng.random(9).astype(np.float32)
        y = rng.random(9).astype(np.float32)
        np.testing.assert_allclose(np.asarray(linalg.axpy(2.0, x, y)), y + 2 * x, rtol=1e-6)
        np.testing.assert_allclose(float(linalg.dot(x, y)), x @ y, rtol=1e-5)


class TestMapReduce:
    def test_norms(self, rng):
        m = rng.standard_normal((6, 8)).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(linalg.row_norm(m)), np.linalg.norm(m, axis=1), rtol=1e-5
        )
        np.testing.assert_allclose(
            np.asarray(linalg.row_norm(m, sqrt=False)), (m**2).sum(1), rtol=1e-5
        )
        np.testing.assert_allclose(
            np.asarray(linalg.col_norm(m, linalg.NormType.L1)), np.abs(m).sum(0), rtol=1e-5
        )
        np.testing.assert_allclose(
            np.asarray(linalg.norm(m, linalg.NormType.Linf, axis=1)), np.abs(m).max(1), rtol=1e-6
        )

    def test_normalize(self, rng):
        m = rng.standard_normal((5, 6)).astype(np.float32)
        out = np.asarray(linalg.normalize(m))
        np.testing.assert_allclose(np.linalg.norm(out, axis=1), 1.0, rtol=1e-5)

    def test_reduce_custom(self, rng):
        m = rng.random((4, 5)).astype(np.float32)
        out = np.asarray(linalg.reduce(m, axis=1, main_op=jnp.square, final_op=jnp.sqrt))
        np.testing.assert_allclose(out, np.linalg.norm(m, axis=1), rtol=1e-5)

    def test_reduce_rows_by_key(self, rng):
        m = rng.random((10, 4)).astype(np.float32)
        keys = rng.integers(0, 3, 10)
        out = np.asarray(linalg.reduce_rows_by_key(m, keys, 3))
        want = np.zeros((3, 4), np.float32)
        for i, k in enumerate(keys):
            want[k] += m[i]
        np.testing.assert_allclose(out, want, rtol=1e-5, atol=1e-6)

    def test_reduce_rows_by_key_weighted(self, rng):
        m = rng.random((10, 4)).astype(np.float32)
        keys = rng.integers(0, 3, 10)
        w = rng.random(10).astype(np.float32)
        out = np.asarray(linalg.reduce_rows_by_key(m, keys, 3, weights=w))
        want = np.zeros((3, 4), np.float32)
        for i, k in enumerate(keys):
            want[k] += w[i] * m[i]
        np.testing.assert_allclose(out, want, rtol=1e-4, atol=1e-5)

    def test_reduce_cols_by_key(self, rng):
        m = rng.random((4, 10)).astype(np.float32)
        keys = rng.integers(0, 3, 10)
        out = np.asarray(linalg.reduce_cols_by_key(m, keys, 3))
        want = np.zeros((4, 3), np.float32)
        for j, k in enumerate(keys):
            want[:, k] += m[:, j]
        np.testing.assert_allclose(out, want, rtol=1e-5, atol=1e-6)

    def test_mse(self, rng):
        a = rng.random(20).astype(np.float32)
        b = rng.random(20).astype(np.float32)
        np.testing.assert_allclose(
            float(linalg.mean_squared_error(a, b)), ((a - b) ** 2).mean(), rtol=1e-5
        )

    def test_matrix_vector_op(self, rng):
        m = rng.random((3, 5)).astype(np.float32)
        v = rng.random(5).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(linalg.matrix_vector_op(m, v, jnp.multiply)), m * v[None, :], rtol=1e-6
        )


class TestSolvers:
    def test_eigh(self, rng):
        a = rng.standard_normal((8, 8)).astype(np.float32)
        a = a @ a.T + 8 * np.eye(8, dtype=np.float32)
        w, v = linalg.eigh(a)
        w, v = np.asarray(w), np.asarray(v)
        np.testing.assert_allclose(a @ v, v * w[None, :], atol=1e-3)
        assert (np.diff(w) >= -1e-4).all()  # ascending

    def test_qr(self, rng):
        a = rng.standard_normal((10, 4)).astype(np.float32)
        q, r = linalg.qr(a)
        q, r = np.asarray(q), np.asarray(r)
        np.testing.assert_allclose(q @ r, a, atol=1e-4)
        np.testing.assert_allclose(q.T @ q, np.eye(4), atol=1e-4)

    def test_svd(self, rng):
        a = rng.standard_normal((8, 5)).astype(np.float32)
        u, s, vt = linalg.svd(a)
        np.testing.assert_allclose(
            np.asarray(u) @ np.diag(np.asarray(s)) @ np.asarray(vt), a, atol=1e-4
        )

    def test_rsvd_recovers_low_rank(self, rng):
        # exact low-rank matrix: rsvd must recover the spectrum
        u = rng.standard_normal((60, 4)).astype(np.float32)
        v = rng.standard_normal((4, 30)).astype(np.float32)
        a = u @ v
        _, s_full, _ = np.linalg.svd(a)
        uu, s, vvt = linalg.rsvd(a, k=4, p=8, n_iter=3)
        np.testing.assert_allclose(np.asarray(s), s_full[:4], rtol=1e-3)
        approx = np.asarray(uu) @ np.diag(np.asarray(s)) @ np.asarray(vvt)
        np.testing.assert_allclose(approx, a, atol=1e-2)

    def test_lstsq(self, rng):
        a = rng.standard_normal((30, 5)).astype(np.float32)
        w = rng.standard_normal(5).astype(np.float32)
        b = a @ w
        got = np.asarray(linalg.lstsq(a, b))
        np.testing.assert_allclose(got, w, atol=1e-3)

    def test_cholesky_r1_update(self, rng):
        a = rng.standard_normal((6, 6)).astype(np.float32)
        a = a @ a.T + 6 * np.eye(6, dtype=np.float32)
        x = rng.standard_normal(6).astype(np.float32)
        l = np.linalg.cholesky(a)
        l_up = np.asarray(linalg.cholesky_r1_update(l, x))
        np.testing.assert_allclose(l_up @ l_up.T, a + np.outer(x, x), atol=1e-3)
