"""IVF-Flat tests — recall-threshold acceptance vs brute force, the
reference's ANN test strategy (cpp/test/neighbors/ann_ivf_flat.cuh;
python test_ivf_flat via pylibraft)."""

import numpy as np
import pytest
from scipy.spatial import distance as sp_dist

from raft_tpu.neighbors import ivf_flat
from raft_tpu.random import make_blobs


def _recall(got_ids, true_ids):
    hits = 0
    for g, t in zip(got_ids, true_ids):
        hits += len(set(g.tolist()) & set(t.tolist()))
    return hits / true_ids.size


@pytest.fixture(scope="module")
def data():
    x, _ = make_blobs(5000, 32, n_clusters=50, cluster_std=2.0, seed=0)
    q, _ = make_blobs(100, 32, n_clusters=50, cluster_std=2.0, seed=1)
    return np.asarray(x), np.asarray(q)


class TestBuild:
    def test_index_structure(self, data):
        x, _ = data
        idx = ivf_flat.build(ivf_flat.IndexParams(n_lists=64, seed=0), x)
        assert idx.n_lists == 64
        assert idx.dim == 32
        assert idx.size == 5000
        sizes = np.asarray(idx.list_sizes)
        assert sizes.sum() == 5000
        assert sizes.min() > 0  # balanced kmeans must not leave empty lists
        # every real slot has a valid id; padding is -1
        ids = np.asarray(idx.list_ids)
        for l in range(64):
            assert (ids[l, : sizes[l]] >= 0).all()
            assert (ids[l, sizes[l]:] == -1).all()

    def test_ids_are_permutation(self, data):
        x, _ = data
        idx = ivf_flat.build(ivf_flat.IndexParams(n_lists=32), x)
        ids = np.asarray(idx.list_ids)
        real = ids[ids >= 0]
        assert sorted(real.tolist()) == list(range(5000))

    def test_list_contents_match_dataset(self, data):
        x, _ = data
        idx = ivf_flat.build(ivf_flat.IndexParams(n_lists=16), x)
        ids = np.asarray(idx.list_ids)
        dat = np.asarray(idx.list_data)
        l, s = 3, 0
        for s in range(int(np.asarray(idx.list_sizes)[l])):
            np.testing.assert_allclose(dat[l, s], x[ids[l, s]], rtol=1e-6)


class TestSearch:
    def test_high_probe_recall(self, data):
        """All lists probed → exact search (recall 1)."""
        x, q = data
        idx = ivf_flat.build(ivf_flat.IndexParams(n_lists=32, seed=0), x)
        d, i = ivf_flat.search(ivf_flat.SearchParams(n_probes=32), idx, q, k=10)
        true_d = sp_dist.cdist(q, x, "sqeuclidean")
        true_i = np.argsort(true_d, 1)[:, :10]
        assert _recall(np.asarray(i), true_i) > 0.999
        np.testing.assert_allclose(
            np.sort(np.asarray(d), 1), np.sort(np.take_along_axis(true_d, true_i, 1), 1),
            atol=1e-2, rtol=1e-3,
        )

    def test_partial_probe_recall(self, data):
        x, q = data
        idx = ivf_flat.build(ivf_flat.IndexParams(n_lists=64, seed=0), x)
        _, i = ivf_flat.search(ivf_flat.SearchParams(n_probes=16), idx, q, k=10)
        true_i = np.argsort(sp_dist.cdist(q, x, "sqeuclidean"), 1)[:, :10]
        rec = _recall(np.asarray(i), true_i)
        assert rec > 0.9, rec

    def test_recall_grows_with_probes(self, data):
        x, q = data
        idx = ivf_flat.build(ivf_flat.IndexParams(n_lists=64, seed=0), x)
        true_i = np.argsort(sp_dist.cdist(q, x, "sqeuclidean"), 1)[:, :10]
        recalls = []
        for p in (1, 4, 16, 64):
            _, i = ivf_flat.search(ivf_flat.SearchParams(n_probes=p), idx, q, k=10)
            recalls.append(_recall(np.asarray(i), true_i))
        assert recalls == sorted(recalls), recalls
        assert recalls[-1] > 0.999

    def test_inner_product_metric(self, data):
        x, q = data
        idx = ivf_flat.build(
            ivf_flat.IndexParams(n_lists=32, metric="inner_product", seed=0), x
        )
        _, i = ivf_flat.search(ivf_flat.SearchParams(n_probes=32), idx, q, k=5)
        true_i = np.argsort(-(q @ x.T), 1)[:, :5]
        assert _recall(np.asarray(i), true_i) > 0.95

    def test_sqrt_metric_values(self, data):
        x, q = data
        idx = ivf_flat.build(ivf_flat.IndexParams(n_lists=16, metric="euclidean"), x)
        d, i = ivf_flat.search(ivf_flat.SearchParams(n_probes=16), idx, q, k=5)
        got = np.asarray(d)[:, 0]
        want = sp_dist.cdist(q, x, "euclidean").min(1)
        np.testing.assert_allclose(got, want, atol=1e-3, rtol=1e-3)


class TestExtend:
    def test_extend_adds_vectors(self, data):
        x, q = data
        idx = ivf_flat.build(ivf_flat.IndexParams(n_lists=32, seed=0), x[:4000])
        idx = ivf_flat.extend(idx, x[4000:], np.arange(4000, 5000, dtype=np.int32))
        assert idx.size == 5000
        _, i = ivf_flat.search(ivf_flat.SearchParams(n_probes=32), idx, q, k=10)
        true_i = np.argsort(sp_dist.cdist(q, x, "sqeuclidean"), 1)[:, :10]
        assert _recall(np.asarray(i), true_i) > 0.999

    def test_build_without_data_then_extend(self, data):
        x, q = data
        idx = ivf_flat.build(
            ivf_flat.IndexParams(n_lists=16, add_data_on_build=False, seed=0), x
        )
        assert idx.size == 0
        idx = ivf_flat.extend(idx, x, np.arange(5000, dtype=np.int32))
        assert idx.size == 5000


class TestSerialize:
    def test_roundtrip(self, tmp_path, data):
        x, q = data
        idx = ivf_flat.build(ivf_flat.IndexParams(n_lists=16, seed=0), x)
        path = str(tmp_path / "index.bin")
        ivf_flat.save(idx, path)
        idx2 = ivf_flat.load(path)
        d1, i1 = ivf_flat.search(ivf_flat.SearchParams(n_probes=4), idx, q, k=5)
        d2, i2 = ivf_flat.search(ivf_flat.SearchParams(n_probes=4), idx2, q, k=5)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
        np.testing.assert_allclose(np.asarray(d1), np.asarray(d2), rtol=1e-6)

    def test_wrong_file_tag(self, tmp_path):
        from raft_tpu.core import RaftError, serialize_scalar

        path = str(tmp_path / "bad.bin")
        with open(path, "wb") as f:
            serialize_scalar(f, "ivf_pq")
        with pytest.raises(RaftError, match="not an ivf_flat"):
            ivf_flat.load(path)
