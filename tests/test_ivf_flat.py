"""IVF-Flat tests — recall-threshold acceptance vs brute force, the
reference's ANN test strategy (cpp/test/neighbors/ann_ivf_flat.cuh;
python test_ivf_flat via pylibraft)."""

import numpy as np
import pytest
from scipy.spatial import distance as sp_dist

from raft_tpu.neighbors import ivf_flat
from raft_tpu.random import make_blobs


def _recall(got_ids, true_ids):
    hits = 0
    for g, t in zip(got_ids, true_ids):
        hits += len(set(g.tolist()) & set(t.tolist()))
    return hits / true_ids.size


@pytest.fixture(scope="module")
def data():
    x, _ = make_blobs(5000, 32, n_clusters=50, cluster_std=2.0, seed=0)
    q, _ = make_blobs(100, 32, n_clusters=50, cluster_std=2.0, seed=1)
    return np.asarray(x), np.asarray(q)


class TestBuild:
    def test_index_structure(self, data):
        x, _ = data
        # split_factor high enough that no list splits: exact n_lists holds
        idx = ivf_flat.build(ivf_flat.IndexParams(n_lists=64, seed=0, split_factor=16.0), x)
        assert idx.n_lists == 64
        assert idx.dim == 32
        assert idx.size == 5000
        sizes = np.asarray(idx.list_sizes)
        assert sizes.sum() == 5000
        assert sizes.min() > 0  # balanced kmeans must not leave empty lists
        # every real slot has a valid id; padding is -1
        ids = np.asarray(idx.list_ids)
        for l in range(64):
            assert (ids[l, : sizes[l]] >= 0).all()
            assert (ids[l, sizes[l]:] == -1).all()

    def test_index_structure_default_split(self, data):
        """Default split_factor may split hot lists into sub-lists sharing a
        center; size bookkeeping and id/padding invariants must still hold."""
        x, _ = data
        idx = ivf_flat.build(ivf_flat.IndexParams(n_lists=64, seed=0), x)
        assert idx.n_lists >= 64
        assert idx.size == 5000
        sizes = np.asarray(idx.list_sizes)
        assert sizes.sum() == 5000
        ids = np.asarray(idx.list_ids)
        for l in range(idx.n_lists):
            assert (ids[l, : sizes[l]] >= 0).all()
            assert (ids[l, sizes[l]:] == -1).all()

    def test_ids_are_permutation(self, data):
        x, _ = data
        idx = ivf_flat.build(ivf_flat.IndexParams(n_lists=32), x)
        ids = np.asarray(idx.list_ids)
        real = ids[ids >= 0]
        assert sorted(real.tolist()) == list(range(5000))

    def test_list_contents_match_dataset(self, data):
        x, _ = data
        idx = ivf_flat.build(ivf_flat.IndexParams(n_lists=16), x)
        ids = np.asarray(idx.list_ids)
        dat = np.asarray(idx.list_data)
        l, s = 3, 0
        for s in range(int(np.asarray(idx.list_sizes)[l])):
            np.testing.assert_allclose(dat[l, s], x[ids[l, s]], rtol=1e-6)


class TestSearch:
    def test_high_probe_recall(self, data):
        """All lists probed → exact search (recall 1)."""
        x, q = data
        idx = ivf_flat.build(ivf_flat.IndexParams(n_lists=32, seed=0), x)
        d, i = ivf_flat.search(ivf_flat.SearchParams(n_probes=32), idx, q, k=10)
        true_d = sp_dist.cdist(q, x, "sqeuclidean")
        true_i = np.argsort(true_d, 1)[:, :10]
        assert _recall(np.asarray(i), true_i) > 0.999
        np.testing.assert_allclose(
            np.sort(np.asarray(d), 1), np.sort(np.take_along_axis(true_d, true_i, 1), 1),
            atol=1e-2, rtol=1e-3,
        )

    def test_partial_probe_recall(self, data):
        x, q = data
        idx = ivf_flat.build(ivf_flat.IndexParams(n_lists=64, seed=0), x)
        _, i = ivf_flat.search(ivf_flat.SearchParams(n_probes=16), idx, q, k=10)
        true_i = np.argsort(sp_dist.cdist(q, x, "sqeuclidean"), 1)[:, :10]
        rec = _recall(np.asarray(i), true_i)
        assert rec > 0.9, rec

    def test_recall_grows_with_probes(self, data):
        x, q = data
        idx = ivf_flat.build(ivf_flat.IndexParams(n_lists=64, seed=0), x)
        true_i = np.argsort(sp_dist.cdist(q, x, "sqeuclidean"), 1)[:, :10]
        recalls = []
        for p in (1, 4, 16, 64):
            _, i = ivf_flat.search(ivf_flat.SearchParams(n_probes=p), idx, q, k=10)
            recalls.append(_recall(np.asarray(i), true_i))
        assert recalls == sorted(recalls), recalls
        assert recalls[-1] > 0.999

    def test_inner_product_metric(self, data):
        x, q = data
        idx = ivf_flat.build(
            ivf_flat.IndexParams(n_lists=32, metric="inner_product", seed=0), x
        )
        _, i = ivf_flat.search(ivf_flat.SearchParams(n_probes=32), idx, q, k=5)
        true_i = np.argsort(-(q @ x.T), 1)[:, :5]
        assert _recall(np.asarray(i), true_i) > 0.95

    def test_sqrt_metric_values(self, data):
        x, q = data
        idx = ivf_flat.build(ivf_flat.IndexParams(n_lists=16, metric="euclidean"), x)
        d, i = ivf_flat.search(ivf_flat.SearchParams(n_probes=16), idx, q, k=5)
        got = np.asarray(d)[:, 0]
        want = sp_dist.cdist(q, x, "euclidean").min(1)
        np.testing.assert_allclose(got, want, atol=1e-3, rtol=1e-3)


class TestExtend:
    def test_extend_adds_vectors(self, data):
        x, q = data
        idx = ivf_flat.build(ivf_flat.IndexParams(n_lists=32, seed=0), x[:4000])
        idx = ivf_flat.extend(idx, x[4000:], np.arange(4000, 5000, dtype=np.int32))
        assert idx.size == 5000
        _, i = ivf_flat.search(ivf_flat.SearchParams(n_probes=32), idx, q, k=10)
        true_i = np.argsort(sp_dist.cdist(q, x, "sqeuclidean"), 1)[:, :10]
        assert _recall(np.asarray(i), true_i) > 0.999

    def test_build_without_data_then_extend(self, data):
        x, q = data
        idx = ivf_flat.build(
            ivf_flat.IndexParams(n_lists=16, add_data_on_build=False, seed=0), x
        )
        assert idx.size == 0
        idx = ivf_flat.extend(idx, x, np.arange(5000, dtype=np.int32))
        assert idx.size == 5000


class TestSerialize:
    def test_roundtrip(self, tmp_path, data):
        x, q = data
        idx = ivf_flat.build(ivf_flat.IndexParams(n_lists=16, seed=0), x)
        path = str(tmp_path / "index.bin")
        ivf_flat.save(idx, path)
        idx2 = ivf_flat.load(path)
        d1, i1 = ivf_flat.search(ivf_flat.SearchParams(n_probes=4), idx, q, k=5)
        d2, i2 = ivf_flat.search(ivf_flat.SearchParams(n_probes=4), idx2, q, k=5)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
        np.testing.assert_allclose(np.asarray(d1), np.asarray(d2), rtol=1e-6)

    def test_wrong_file_tag(self, tmp_path):
        from raft_tpu.core import RaftError, serialize_scalar

        path = str(tmp_path / "bad.bin")
        with open(path, "wb") as f:
            serialize_scalar(f, "ivf_pq")
        with pytest.raises(RaftError, match="not an ivf_flat"):
            ivf_flat.load(path)


def test_bfloat16_list_storage(rng, tmp_path):
    """bf16 list storage (halved scan bandwidth) keeps near-exact recall and
    survives serialization."""
    import jax.numpy as jnp
    from raft_tpu.neighbors import ivf_flat

    n, d, m, k = 1500, 24, 40, 8
    x = rng.random((n, d)).astype(np.float32)
    q = rng.random((m, d)).astype(np.float32)
    index = ivf_flat.build(
        ivf_flat.IndexParams(n_lists=16, seed=0, list_dtype="bfloat16"), x
    )
    assert index.list_data.dtype == jnp.bfloat16
    params = ivf_flat.SearchParams(n_probes=16)  # exhaustive
    _, ids = ivf_flat.search(params, index, q, k)
    d2 = ((q[:, None, :].astype(np.float64) - x[None]) ** 2).sum(-1)
    want = np.argsort(d2, 1)[:, :k]
    ids = np.asarray(ids)
    recall = np.mean([len(set(ids[i]) & set(want[i])) / k for i in range(m)])
    assert recall > 0.95, recall

    # extend keeps the storage dtype; save/load roundtrip
    index2 = ivf_flat.extend(index, rng.random((64, d)).astype(np.float32))
    assert index2.list_data.dtype == jnp.bfloat16
    path = str(tmp_path / "idx.bin")
    ivf_flat.save(index2, path)
    loaded = ivf_flat.load(path)
    assert loaded.list_data.dtype == jnp.bfloat16


class TestInt8Storage:
    """int8/uint8 dataset support end-to-end (VERDICT r4 #2; reference:
    ivf_flat int8_t/uint8_t instantiations,
    cpp/src/neighbors/ivf_flat_build_uint8_t_int64_t.cu). Exhaustive probing
    makes the search EXACT for raw 8-bit data — parity is vs the f64 ground
    truth, not a recall threshold."""

    @pytest.fixture(scope="class")
    def idata(self):
        rng = np.random.default_rng(3)
        # clustered bytes: blob centers + noise, clipped to [0, 255]
        centers = rng.integers(40, 215, (24, 32))
        lab = rng.integers(0, 24, 3000)
        x = np.clip(centers[lab] + rng.normal(0, 12, (3000, 32)), 0, 255)
        qlab = rng.integers(0, 24, 50)
        q = np.clip(centers[qlab] + rng.normal(0, 12, (50, 32)), 0, 255)
        return x.astype(np.uint8), q.astype(np.uint8)

    @pytest.mark.parametrize("dt", [np.uint8, np.int8])
    def test_build_search_exact(self, idata, dt):
        import jax.numpy as jnp

        xu, qu = idata
        x = xu if dt == np.uint8 else (xu.astype(np.int16) - 128).astype(np.int8)
        q = qu if dt == np.uint8 else (qu.astype(np.int16) - 128).astype(np.int8)
        idx = ivf_flat.build(ivf_flat.IndexParams(n_lists=16, seed=0), x)
        assert idx.list_data.dtype == jnp.int8  # auto int8 storage
        assert idx.data_kind == dt.__name__
        d2g, ids = ivf_flat.search(
            ivf_flat.SearchParams(n_probes=idx.n_lists), idx, q, 10)
        d2 = ((q[:, None, :].astype(np.float64)
               - x[None].astype(np.float64)) ** 2).sum(-1)
        want = np.argsort(d2, 1)[:, :10]
        rec = _recall(np.asarray(ids), want)
        assert rec > 0.999, rec
        # exact integer distances
        np.testing.assert_array_equal(
            np.asarray(d2g), np.take_along_axis(d2, np.asarray(ids), 1))

    def test_float_queries_on_uint8_index(self, idata):
        xu, qu = idata
        idx = ivf_flat.build(ivf_flat.IndexParams(n_lists=16, seed=0), xu)
        _, ids_int = ivf_flat.search(
            ivf_flat.SearchParams(n_probes=16), idx, qu, 10)
        _, ids_f = ivf_flat.search(
            ivf_flat.SearchParams(n_probes=16), idx,
            qu.astype(np.float32), 10)
        np.testing.assert_array_equal(np.asarray(ids_int), np.asarray(ids_f))

    def test_extend_and_serialize(self, idata, tmp_path):
        import jax.numpy as jnp

        xu, qu = idata
        idx = ivf_flat.build(ivf_flat.IndexParams(n_lists=16, seed=0),
                             xu[:2000])
        idx = ivf_flat.extend(idx, xu[2000:])
        assert idx.data_kind == "uint8" and idx.list_data.dtype == jnp.int8
        p = str(tmp_path / "u8.bin")
        ivf_flat.save(idx, p)
        loaded = ivf_flat.load(p)
        assert loaded.data_kind == "uint8"
        d1, i1 = ivf_flat.search(ivf_flat.SearchParams(n_probes=16), idx, qu, 5)
        d2, i2 = ivf_flat.search(ivf_flat.SearchParams(n_probes=16), loaded, qu, 5)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))

    def test_wrong_dtype_guards(self, idata):
        from raft_tpu.core import RaftError

        xu, qu = idata
        idx = ivf_flat.build(ivf_flat.IndexParams(n_lists=16, seed=0), xu)
        with pytest.raises(RaftError, match="stores uint8"):
            ivf_flat.extend(idx, (xu[:10].astype(np.int16) - 128).astype(np.int8))
        with pytest.raises(RaftError, match="stores uint8"):
            ivf_flat.search(ivf_flat.SearchParams(n_probes=4), idx,
                            (qu.astype(np.int16) - 128).astype(np.int8), 5)
        with pytest.raises(RaftError, match="float data is IVF-PQ"):
            ivf_flat.build(ivf_flat.IndexParams(n_lists=16, list_dtype="int8"),
                           xu.astype(np.float32))
        with pytest.raises(RaftError, match="inner_product"):
            ivf_flat.build(ivf_flat.IndexParams(
                n_lists=16, metric="inner_product"), xu)

    def test_explicit_float_storage_of_uint8(self, idata):
        """list_dtype='float32' on uint8 input keeps the float pipeline."""
        import jax.numpy as jnp

        xu, qu = idata
        idx = ivf_flat.build(
            ivf_flat.IndexParams(n_lists=16, seed=0, list_dtype="float32"), xu)
        assert idx.data_kind == "float32"
        assert idx.list_data.dtype == jnp.float32
        _, ids = ivf_flat.search(
            ivf_flat.SearchParams(n_probes=16), idx, qu.astype(np.float32), 10)
        d2 = ((qu[:, None, :].astype(np.float64)
               - xu[None].astype(np.float64)) ** 2).sum(-1)
        want = np.argsort(d2, 1)[:, :10]
        assert _recall(np.asarray(ids), want) > 0.999


def test_spatial_split_recall_on_skewed_population(rng):
    """A Zipf-style mega-cluster must stay searchable at LOW probe counts:
    oversized lists split into principal-axis slabs with their own
    member-mean centers, so a query's coarse scores rank nearby slabs first
    (r05 heavytail fix). With the old order-split + duplicated centers,
    neighbors scattered uniformly over ~population/cap identical-score
    sub-lists and p=4 of ~13 capped recall near 4/13."""
    n_big, d = 4000, 16
    centers = rng.random((21, d)).astype(np.float32) * 20
    big = (centers[0] + rng.normal(0, 1.0, (n_big, d))).astype(np.float32)
    rest = np.concatenate([
        (centers[i] + rng.normal(0, 0.3, (100, d))).astype(np.float32)
        for i in range(1, 21)])
    x = np.concatenate([big, rest])
    perm = rng.permutation(len(x))
    x = x[perm]
    idx = ivf_flat.build(ivf_flat.IndexParams(n_lists=21, seed=0), x)
    assert idx.n_lists > 21  # the mega-cluster split
    q = big[:64]
    d2 = ((q[:, None, :].astype(np.float64) - x[None]) ** 2).sum(-1)
    want = np.argsort(d2, 1)[:, :10]
    _, ids = ivf_flat.search(ivf_flat.SearchParams(n_probes=4), idx, q, 10)
    rec = _recall(np.asarray(ids), want)
    assert rec > 0.7, rec  # order-split ceiling here is ~4/13 = 0.31


def test_oversized_list_splitting(rng):
    """A pathologically hot cluster must not inflate every list's capacity:
    it splits into sub-lists sharing the center (_list_utils.split_oversized)."""
    from raft_tpu.neighbors import ivf_flat

    # 1 dense blob (80% of data) + spread: massive skew
    hot = rng.normal(0, 0.01, (1600, 8)).astype(np.float32)
    rest = rng.normal(5, 2.0, (400, 8)).astype(np.float32)
    x = np.concatenate([hot, rest])
    index = ivf_flat.build(ivf_flat.IndexParams(n_lists=16, seed=0), x)
    mean = 2000 / 16
    assert index.capacity <= 2 * mean + 8, index.capacity
    assert index.size == 2000  # nothing dropped

    # search stays correct: probing everything == exact
    q = x[::100]
    params = ivf_flat.SearchParams(n_probes=index.n_lists)
    dists, ids = ivf_flat.search(params, index, q, 5)
    d2 = ((q[:, None, :].astype(np.float64) - x[None]) ** 2).sum(-1)
    want = np.sort(d2, 1)[:, :5]
    np.testing.assert_allclose(np.sort(np.asarray(dists), 1), want, atol=1e-2, rtol=1e-3)


def test_split_oversized_unit(rng):
    """Unit contract of _list_utils.split_oversized: capacity-bounded sub-list
    relabeling that preserves membership and parent ordering."""
    import jax.numpy as jnp
    from raft_tpu.neighbors._list_utils import split_oversized

    # list 0: 20 members, list 1: 3, list 2: 9; cap 8
    labels = jnp.asarray(np.array([0] * 20 + [1] * 3 + [2] * 9, np.int32))
    new_labels, rep = split_oversized(labels, 3, 8)
    assert rep.tolist() == [3, 1, 2]
    nl = np.asarray(new_labels)
    # list 0 → sub-lists 0,1,2; list 1 → 3; list 2 → 4,5
    assert set(nl[:20]) == {0, 1, 2}
    assert set(nl[20:23]) == {3}
    assert set(nl[23:]) == {4, 5}
    # every sub-list holds at most cap members
    assert np.bincount(nl).max() <= 8


def test_forced_split_via_extend(rng):
    """Extending a small-list index with skewed data triggers sub-list
    splitting end-to-end (capacity stays bounded, search stays exact)."""
    import jax.numpy as jnp
    from raft_tpu.neighbors import ivf_flat

    base = rng.random((64, 6)).astype(np.float32)
    index = ivf_flat.build(ivf_flat.IndexParams(n_lists=8, seed=0), base)
    # all new points land in one list: duplicates of one base vector
    hot = np.tile(base[:1], (400, 1)) + rng.normal(0, 1e-3, (400, 6)).astype(np.float32)
    index2 = ivf_flat.extend(index, hot)
    mean = (64 + 400) / 8
    assert index2.capacity <= 2 * mean + 8, index2.capacity
    assert index2.n_lists > 8  # the hot list split
    assert index2.size == 464
    # exact search across the split index
    q = np.concatenate([base[:4], hot[:4]])
    dists, ids = ivf_flat.search(
        ivf_flat.SearchParams(n_probes=index2.n_lists), index2, q, 3
    )
    all_x = np.concatenate([base, hot])
    d2 = ((q[:, None, :].astype(np.float64) - all_x[None]) ** 2).sum(-1)
    np.testing.assert_allclose(
        np.sort(np.asarray(dists), 1), np.sort(d2, 1)[:, :3], atol=1e-3, rtol=1e-3
    )


def test_extend_inherits_split_policy(data):
    """extend() must reuse the build-time split_factor (persisted on the
    index), so a no-split build stays no-split through incremental adds."""
    x, _ = data
    idx = ivf_flat.build(ivf_flat.IndexParams(n_lists=64, seed=0, split_factor=16.0), x)
    assert idx.n_lists == 64
    rng = np.random.default_rng(7)
    idx2 = ivf_flat.extend(idx, rng.random((400, 32)).astype(np.float32))
    assert idx2.n_lists == 64  # would split under the 1.3 default
    assert idx2.split_factor == 16.0
    assert idx2.size == idx.size + 400


def test_search_inside_enclosing_jit(rng):
    """Users may wrap search() in their own jax.jit (the bench does); the
    index is then a closure constant and host-side int() properties must not
    stage into the trace."""
    import jax
    import jax.numpy as jnp

    x = jnp.asarray(rng.random((600, 8)).astype(np.float32))
    idx = ivf_flat.build(ivf_flat.IndexParams(n_lists=8, seed=0), x)
    q = x[:5]
    d0, i0 = ivf_flat.search(ivf_flat.SearchParams(n_probes=4), idx, q, 3)
    d1, i1 = jax.jit(
        lambda qq: ivf_flat.search(ivf_flat.SearchParams(n_probes=4), idx, qq, 3))(q)
    np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
    # index as a traced jit ARGUMENT (pytree-flattened): exercises the
    # Tracer-guard branch that skips the data-dependent emptiness check
    d2, i2 = jax.jit(
        lambda ix, qq: ivf_flat.search(ivf_flat.SearchParams(n_probes=4), ix, qq, 3))(idx, q)
    np.testing.assert_array_equal(np.asarray(i0), np.asarray(i2))

    # same contract for ivf_pq
    from raft_tpu.neighbors import ivf_pq

    pq = ivf_pq.build(ivf_pq.IndexParams(n_lists=8, pq_dim=4, seed=0), x)
    p0 = ivf_pq.search(ivf_pq.SearchParams(n_probes=4), pq, q, 3)
    p1 = jax.jit(
        lambda qq: ivf_pq.search(ivf_pq.SearchParams(n_probes=4), pq, qq, 3))(q)
    p2 = jax.jit(
        lambda ix, qq: ivf_pq.search(ivf_pq.SearchParams(n_probes=4), ix, qq, 3))(pq, q)
    np.testing.assert_array_equal(np.asarray(p0[1]), np.asarray(p1[1]))
    np.testing.assert_array_equal(np.asarray(p0[1]), np.asarray(p2[1]))


class TestSampleFilterEquivalence:
    """Filtered search vs prefiltered rebuild (ISSUE 5 satellite): at
    exhaustive probes, searching with `sample_filter=keep` must equal
    building a fresh index over ONLY the kept rows — same neighbor ids
    (mapped through the kept-row order), same distances. Holds for float
    and byte storage, and pins the shared -1/+inf underfill contract."""

    def test_filtered_equals_prefiltered_rebuild(self, data):
        x, q = data
        rng = np.random.default_rng(5)
        keep = rng.random(x.shape[0]) > 0.4
        idx = ivf_flat.build(ivf_flat.IndexParams(n_lists=16, seed=0), x)
        d_f, i_f = ivf_flat.search(
            ivf_flat.SearchParams(n_probes=64), idx, q, 10, sample_filter=keep)
        pre = ivf_flat.build(ivf_flat.IndexParams(n_lists=16, seed=0), x[keep])
        d_p, i_p = ivf_flat.search(
            ivf_flat.SearchParams(n_probes=64), pre, q, 10)
        kept_rows = np.nonzero(keep)[0]
        i_p = kept_rows[np.asarray(i_p)]  # positions -> original row ids
        np.testing.assert_array_equal(np.asarray(i_f), i_p)
        np.testing.assert_allclose(np.asarray(d_f), np.asarray(d_p),
                                   rtol=1e-4, atol=1e-3)

    def test_filtered_equals_prefiltered_rebuild_bytes(self):
        rng = np.random.default_rng(7)
        x = rng.integers(0, 256, (1500, 16), dtype=np.uint8)
        q = rng.integers(0, 256, (20, 16), dtype=np.uint8)
        keep = rng.random(1500) > 0.5
        idx = ivf_flat.build(ivf_flat.IndexParams(n_lists=8, seed=0), x)
        d_f, i_f = ivf_flat.search(
            ivf_flat.SearchParams(n_probes=32), idx, q, 10, sample_filter=keep)
        pre = ivf_flat.build(ivf_flat.IndexParams(n_lists=8, seed=0), x[keep])
        d_p, i_p = ivf_flat.search(
            ivf_flat.SearchParams(n_probes=32), pre, q, 10)
        i_p = np.nonzero(keep)[0][np.asarray(i_p)]
        np.testing.assert_array_equal(np.asarray(i_f), i_p)
        np.testing.assert_allclose(np.asarray(d_f), np.asarray(d_p),
                                   rtol=1e-5)

    def test_underfill_sentinels(self, data, check_filter_underfill):
        x, q = data
        idx = ivf_flat.build(ivf_flat.IndexParams(n_lists=16, seed=0), x)
        alive = [9, 480, 3111]
        keep = np.zeros(x.shape[0], bool)
        keep[alive] = True
        d, i = ivf_flat.search(
            ivf_flat.SearchParams(n_probes=64), idx, q, 10, sample_filter=keep)
        check_filter_underfill(d, i, alive, select_min=True)

    def test_underfill_sentinels_inner_product(self, data,
                                               check_filter_underfill):
        x, q = data
        idx = ivf_flat.build(
            ivf_flat.IndexParams(n_lists=16, metric="inner_product", seed=0), x)
        alive = [12, 77]
        keep = np.zeros(x.shape[0], bool)
        keep[alive] = True
        d, i = ivf_flat.search(
            ivf_flat.SearchParams(n_probes=64), idx, q, 10, sample_filter=keep)
        check_filter_underfill(d, i, alive, select_min=False)


class TestMinibatchEm:
    """Mini-batch coarse EM (ISSUE 6): the 100k recall anchor must hold
    within tolerance vs full EM — the build got faster, not worse. The
    heavy 1M case lives in the slow manifest (test_minibatch_em_1m)."""

    def test_minibatch_recall_parity_100k(self):
        import dataclasses

        from raft_tpu.neighbors import brute_force

        n, d, k = 100_000, 32, 10
        x, _ = make_blobs(n, d, n_clusters=500, cluster_std=1.0, seed=7)
        x = np.asarray(x)
        q = x[:300]
        _, gt = brute_force.knn(x, q, k)
        gt = np.asarray(gt)
        base = ivf_flat.IndexParams(n_lists=256, seed=0,
                                    kmeans_batch_rows=8192)
        sp = ivf_flat.SearchParams(n_probes=8)
        recs = {}
        for mode in ("full", "minibatch"):
            idx = ivf_flat.build(
                dataclasses.replace(base, kmeans_train_mode=mode), x)
            _, ids = ivf_flat.search(sp, idx, q, k)
            recs[mode] = _recall(np.asarray(ids), gt)
            del idx
        assert recs["minibatch"] > 0.8, recs
        assert recs["minibatch"] >= recs["full"] - 0.02, recs


@pytest.mark.slow
def test_minibatch_em_auto_at_scale():
    """Heavy case (slow manifest): at 300k the AUTO default resolves to
    mini-batch (trainset 150k > 2 x 65536) — the production default path —
    and the recall anchor holds vs a pinned full-EM build."""
    import dataclasses

    from raft_tpu.cluster.kmeans_balanced import resolve_train_mode
    from raft_tpu.neighbors import brute_force

    n, d, k = 300_000, 32, 10
    assert resolve_train_mode("auto", n // 2, 65536) == "minibatch"
    x, _ = make_blobs(n, d, n_clusters=1000, cluster_std=1.0, seed=5)
    x = np.asarray(x)
    q = x[:200]
    _, gt = brute_force.knn(x, q, k)
    gt = np.asarray(gt)
    base = ivf_flat.IndexParams(n_lists=512, seed=0)  # auto -> minibatch
    sp = ivf_flat.SearchParams(n_probes=8)
    recs = {}
    for mode in ("auto", "full"):
        idx = ivf_flat.build(
            dataclasses.replace(base, kmeans_train_mode=mode), x)
        _, ids = ivf_flat.search(sp, idx, q, k)
        recs[mode] = _recall(np.asarray(ids), gt)
        del idx
    assert recs["auto"] > 0.8, recs
    assert recs["auto"] >= recs["full"] - 0.02, recs
