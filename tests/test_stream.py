"""Mutable-index lifecycle tests (tier-1 ``stream`` marker).

Deterministic by construction: MutableIndex/Compactor take injected clocks
and the compactor is driven via ``run_once()`` — watermark policy, write
visibility and compaction swaps are asserted without wall-clock sleeps.
The two concurrency tests (swap under load, background worker liveness)
use real threads but synchronize on joins/poll deadlines, never timed
sleeps in assertions.
"""

import threading

import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu import stream
from raft_tpu.core.errors import RaftError
from raft_tpu.neighbors import brute_force, cagra, ivf_flat, ivf_pq
from raft_tpu.serve import (IndexRegistry, OverloadedError, SearchService,
                            ServiceClosedError)

pytestmark = pytest.mark.stream


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


@pytest.fixture
def data(rng):
    return rng.standard_normal((240, 16)).astype(np.float32)


@pytest.fixture
def queries(rng):
    return rng.standard_normal((5, 16)).astype(np.float32)


def wrap_bf(x, **kw):
    return stream.MutableIndex(
        brute_force.BruteForce().build(jnp.asarray(x)), **kw)


def bf_gids(live_mat, live_gids, queries, k):
    """Ground truth over an explicit live-row set, mapped to global ids."""
    _, pos = brute_force.knn(jnp.asarray(live_mat), jnp.asarray(queries), k)
    pos = np.asarray(pos)
    return np.where(pos >= 0, np.asarray(live_gids)[np.clip(pos, 0, None)], -1)


# -- ladder / wrap validation -------------------------------------------------

def test_delta_bucket_ladder():
    assert stream.delta_buckets(64) == (8, 16, 32, 64)
    assert stream.delta_buckets(8) == (8,)
    with pytest.raises(RaftError):
        stream.delta_buckets(48)  # not a power of two
    with pytest.raises(RaftError):
        stream.delta_buckets(4)  # below the floor


def test_wrap_validations(data):
    with pytest.raises(RaftError):
        stream.MutableIndex(object())  # not an index
    pq = ivf_pq.build(ivf_pq.IndexParams(n_lists=8, pq_dim=8, seed=0),
                      jnp.asarray(data))
    with pytest.raises(RaftError, match="retain_vectors"):
        # PQ codes cannot reconstruct rows: a retained store needs dataset=
        stream.MutableIndex(pq, retain_vectors=True)
    with pytest.raises(RaftError, match="sealed rows"):
        stream.MutableIndex(pq, dataset=data[:10])


# -- write visibility ---------------------------------------------------------

def test_upsert_visible_before_compaction(data, queries):
    m = wrap_bf(data, delta_capacity=16)
    new = queries[0:1] + 1e-3  # right on top of query 0
    gid = m.upsert(new)
    assert m.stats()["delta_rows"] == 1 and m.stats()["epoch"] == 0
    _, ids = m.search(queries, 5)
    assert int(np.asarray(ids)[0, 0]) == int(gid[0])
    assert m.size == len(data) + 1


def test_delete_invisible_immediately(data, queries):
    m = wrap_bf(data, delta_capacity=16)
    _, ids0 = m.search(queries, 5)
    nn = int(np.asarray(ids0)[0, 0])
    assert m.delete([nn]) == 1
    _, ids1 = m.search(queries, 5)
    assert nn not in np.asarray(ids1)[0]
    # unknown / already-dead ids are counted no-ops
    assert m.delete([nn, 10_000]) == 0


def test_upsert_same_id_replaces_old_vector(data, queries):
    """upsert = tombstone-old + insert-new: the stale copy never surfaces,
    in either the sealed or the delta layer."""
    m = wrap_bf(data, delta_capacity=16)
    _, ids0 = m.search(queries, 5)
    nn = int(np.asarray(ids0)[1, 0])  # a SEALED row
    far = (queries[1:2] * 0.0) + 100.0
    m.upsert(far, ids=[nn])  # replace with a far-away vector
    d1, ids1 = m.search(queries, 5)
    assert nn not in np.asarray(ids1)[1]  # old copy is dead, new copy is far
    # replace a DELTA row under the same id
    m.upsert(queries[1:2] + 1e-3, ids=[nn])
    _, ids2 = m.search(queries, 5)
    assert int(np.asarray(ids2)[1, 0]) == nn
    assert m.size == len(data)  # one live copy per id throughout


def test_underfilled_search_reports_sentinels(data, queries):
    """Stream inherits the shared filtered-underfill contract: when the
    live rows cannot fill k slots, ids are -1 at +inf."""
    m = wrap_bf(data, delta_capacity=16)
    m.delete(np.arange(len(data)))  # everything sealed is dead
    g = m.upsert(queries[0:1] + 1e-3)  # one live delta row
    d, i = m.search(queries, 5)
    d, i = np.asarray(d), np.asarray(i)
    assert (i[:, 0] == int(g[0])).all()
    assert (i[:, 1:] == -1).all() and np.isinf(d[:, 1:]).all()


def test_delta_full_is_overload(data):
    m = wrap_bf(data, delta_capacity=8)
    m.upsert(data[:8] + 0.5)
    with pytest.raises(OverloadedError):  # DeltaFullError subclasses it
        m.upsert(data[:1])
    with pytest.raises(stream.DeltaFullError):
        m.upsert(data[:1])
    m.compact()
    m.upsert(data[:1] + 0.25)  # admission reopens after the fold


# -- unified search parity ----------------------------------------------------

def test_search_matches_fresh_build_over_live_rows(data, queries, rng):
    """The acceptance bit-match: mutable search over (dataset − deleted +
    inserted) equals a fresh brute-force build over exactly the live rows
    — identical ids (after gid mapping), matching distances — WITHOUT any
    compaction (sealed+delta merge path)."""
    m = wrap_bf(data, delta_capacity=64)
    ins = rng.standard_normal((20, 16)).astype(np.float32)
    gids = m.upsert(ins)
    dele = [3, 17, 44, 101, int(gids[4])]
    m.delete(dele)
    live_mask = np.ones(len(data), bool)
    live_mask[[3, 17, 44, 101]] = False
    ins_mask = np.ones(20, bool)
    ins_mask[4] = False
    live_mat = np.concatenate([data[live_mask], ins[ins_mask]])
    live_g = np.concatenate([np.nonzero(live_mask)[0],
                             np.asarray(gids)[ins_mask]])
    want = bf_gids(live_mat, live_g, queries, 10)
    d, got = m.search(queries, 10)
    np.testing.assert_array_equal(np.asarray(got), want)
    dref, _ = brute_force.knn(jnp.asarray(live_mat), jnp.asarray(queries), 10)
    np.testing.assert_allclose(np.asarray(d), np.asarray(dref), rtol=1e-5)


def test_compaction_equals_fresh_build(data, queries, rng):
    """Rebuild compaction folds delta + reclaims tombstones; results stay
    identical to the pre-compaction view and to a fresh build."""
    m = wrap_bf(data, delta_capacity=64)
    gids = m.upsert(rng.standard_normal((10, 16)).astype(np.float32))
    m.delete([0, 1, 2, int(gids[0])])
    d0, i0 = m.search(queries, 8)
    rep = m.compact()
    assert rep["mode"] == "rebuild" and rep["reclaimed"] == 3
    st = m.stats()
    assert st["sealed_dead"] == 0 and st["delta_rows"] == 0
    assert st["epoch"] == 1
    d1, i1 = m.search(queries, 8)
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i0))
    np.testing.assert_allclose(np.asarray(d1), np.asarray(d0), rtol=1e-5)


def test_writes_during_fold_survive_the_swap(data, queries):
    """Compaction folds a snapshot prefix; anything written between the
    snapshot and the swap (simulated here by writing right before compact —
    the swap re-reads all alive bits) is preserved."""
    m = wrap_bf(data, delta_capacity=64)
    g1 = m.upsert(queries[0:1] + 1e-3)
    m.compact()
    # post-swap: folded row is sealed now; delete it THROUGH the new layout
    assert m.delete([int(g1[0])]) == 1
    _, ids = m.search(queries, 5)
    assert int(g1[0]) not in np.asarray(ids)[0]


def test_extend_compaction_ivf_flat_parity(data, queries, rng):
    """IVF-Flat extend-compaction: exhaustive probes make the scan exact,
    so pre/post-compaction results match the brute-force ground truth over
    the live rows; tombstoned sealed slots stay masked after the fold."""
    idx = ivf_flat.build(ivf_flat.IndexParams(n_lists=8, seed=0),
                         jnp.asarray(data))
    # splitting can leave the index with > n_lists lists; probe them ALL so
    # the scan is exhaustive and the bit-match against brute force holds
    m = stream.MutableIndex(idx, search_params=ivf_flat.SearchParams(n_probes=64),
                            delta_capacity=32, retain_vectors=False)
    ins = rng.standard_normal((6, 16)).astype(np.float32)
    gids = m.upsert(ins)
    m.delete([7, 8])
    live_mat = np.concatenate([np.delete(data, [7, 8], axis=0), ins])
    live_g = np.concatenate([np.delete(np.arange(len(data)), [7, 8]),
                             np.asarray(gids)])
    want = bf_gids(live_mat, live_g, queries, 10)
    _, got0 = m.search(queries, 10)
    np.testing.assert_array_equal(np.asarray(got0), want)
    rep = m.compact()
    assert rep["mode"] == "extend"
    assert m.stats()["sealed_dead"] == 2  # extend keeps tombstones masked
    _, got1 = m.search(queries, 10)
    np.testing.assert_array_equal(np.asarray(got1), want)


def test_ivf_pq_compaction_recall_parity(data, queries, rng):
    """IVF-PQ (quantized): compacted results keep recall parity with a
    fresh oracle build over the live rows at the same operating point."""
    params = ivf_pq.IndexParams(n_lists=8, pq_dim=16, seed=0)
    sp = ivf_pq.SearchParams(n_probes=64)  # exhaustive even after splits
    idx = ivf_pq.build(params, jnp.asarray(data))
    m = stream.MutableIndex(idx, search_params=sp, delta_capacity=32)
    ins = rng.standard_normal((12, 16)).astype(np.float32)
    gids = m.upsert(ins)
    m.delete(np.arange(10))
    m.compact()  # extend
    live_mat = np.concatenate([data[10:], ins])
    live_g = np.concatenate([np.arange(10, len(data)), np.asarray(gids)])
    want = bf_gids(live_mat, live_g, queries, 10)
    _, got = m.search(queries, 10)
    got = np.asarray(got)
    oracle = ivf_pq.build(params, jnp.asarray(live_mat))
    _, o_pos = ivf_pq.search(sp, oracle, jnp.asarray(queries), 10)
    o_pos = np.asarray(o_pos)
    o_got = np.where(o_pos >= 0, live_g[np.clip(o_pos, 0, None)], -1)

    def rec(ids):
        return np.mean([len(set(a) & set(b)) / 10 for a, b in zip(ids, want)])

    assert abs(rec(got) - rec(o_got)) <= 0.1  # same quantized regime


def test_cagra_rebuild_compaction(data, queries, rng):
    """CAGRA has no extend: compaction rebuilds from the retained rows
    (auto-recovered from the sealed dataset), reclaiming tombstones."""
    idx = cagra.build(cagra.IndexParams(seed=0), jnp.asarray(data))
    m = stream.MutableIndex(idx, search_params=cagra.SearchParams(itopk_size=32),
                            delta_capacity=32)
    assert m.can_rebuild  # store auto-recovered from the sealed dataset
    with pytest.raises(RaftError, match="rebuild"):
        m.compact(mode="extend")
    g = m.upsert(queries[0:1] + 1e-3)
    _, i0 = m.search(queries, 5)
    nn1 = int(np.asarray(i0)[1, 0])
    m.delete([nn1])
    rep = m.compact()
    assert rep["mode"] == "rebuild" and m.stats()["sealed_dead"] == 0
    _, i1 = m.search(queries, 5)
    assert int(np.asarray(i1)[0, 0]) == int(g[0])
    assert nn1 not in np.asarray(i1)[1]


# -- serialization ------------------------------------------------------------

def test_serialize_roundtrip_mutable_state(data, queries, rng, tmp_path):
    """The FULL mutable state — sealed + live delta + tombstones + id map —
    round-trips; the loaded index searches identically and keeps churning
    (delete/upsert/compact all work on the restored state)."""
    idx = ivf_flat.build(ivf_flat.IndexParams(n_lists=8, seed=0),
                         jnp.asarray(data))
    m = stream.MutableIndex(idx, search_params=ivf_flat.SearchParams(n_probes=8),
                            delta_capacity=32, dataset=data)
    gids = m.upsert(rng.standard_normal((5, 16)).astype(np.float32))
    m.delete([4, 5, int(gids[2])])
    m.compact()
    g2 = m.upsert(rng.standard_normal((3, 16)).astype(np.float32))
    m.delete([11, int(g2[0])])

    p = str(tmp_path / "m.stream")
    stream.save(m, p)
    m2 = stream.load(p, search_params=ivf_flat.SearchParams(n_probes=8))
    assert m2.size == m.size and m2.kind == "ivf_flat"
    # epoch/age are in-process counters (compaction count, clock base) and
    # restart with the new process; everything structural must match
    sa, sb = m.stats(), m2.stats()
    for key in ("live", "sealed_rows", "sealed_dead", "tombstone_ratio",
                "delta_rows", "delta_fill", "delta_bucket"):
        assert sa[key] == sb[key], key
    da, ia = m.search(queries, 10)
    db, ib = m2.search(queries, 10)
    np.testing.assert_array_equal(np.asarray(ia), np.asarray(ib))
    np.testing.assert_allclose(np.asarray(da), np.asarray(db), rtol=1e-6)
    # fresh ids continue where the saved state left off; churn keeps working
    g3 = m2.upsert(queries[0:1] + 1e-3)
    assert int(g3[0]) == m._next_id
    _, i3 = m2.search(queries, 5)
    assert int(np.asarray(i3)[0, 0]) == int(g3[0])
    m2.compact()


def test_stream_file_rejects_other_tags(data, tmp_path):
    m = wrap_bf(data)
    p = str(tmp_path / "m.stream")
    stream.save(m, p)
    with pytest.raises(RaftError, match="not an ivf_flat"):
        ivf_flat.load(p)


# -- byte dtypes --------------------------------------------------------------

def test_byte_mutable_index(rng):
    """int8 sealed + int8 delta: the byte contract holds through the
    mutable layer (byte rows required, float rows refused), and the delta
    scan rides the exact byte kNN path."""
    xb = rng.integers(-128, 128, (200, 16), dtype=np.int8)
    idx = ivf_flat.build(
        ivf_flat.IndexParams(n_lists=4, list_dtype="int8", seed=0), xb)
    m = stream.MutableIndex(idx, search_params=ivf_flat.SearchParams(n_probes=16),
                            delta_capacity=16, retain_vectors=False)
    assert m.query_dtype == "int8"
    with pytest.raises(RaftError, match="int8"):
        m.upsert(np.zeros((1, 16), np.float32))
    q = xb[:3]
    g = m.upsert(q[0:1])  # exact duplicate of query 0
    _, ids = m.search(q, 3)
    got = set(np.asarray(ids)[0].tolist())
    assert int(g[0]) in got and 0 in got  # both zero-distance copies win
    m.compact()  # extend path takes byte rows in the original dtype
    _, ids2 = m.search(q, 3)
    assert int(g[0]) in set(np.asarray(ids2)[0].tolist())


# -- compactor watermarks (injected clock) ------------------------------------

def test_compactor_delta_fill_watermark(data):
    clock = FakeClock()
    m = wrap_bf(data, delta_capacity=16, clock=clock)
    comp = stream.Compactor(
        m, policy=stream.CompactionPolicy(delta_fill=0.5,
                                          tombstone_ratio=None), clock=clock)
    assert comp.due() is None and comp.run_once() is None
    m.upsert(data[:8] + 0.5)  # fill 0.5
    assert comp.due() == "delta_fill"
    rep = comp.run_once()
    assert rep["trigger"] == "delta_fill" and rep["folded"] == 8
    assert comp.due() is None
    assert comp.last_report is rep


def test_compactor_age_watermark(data):
    clock = FakeClock()
    m = wrap_bf(data, delta_capacity=64, clock=clock)
    comp = stream.Compactor(
        m, policy=stream.CompactionPolicy(delta_fill=None,
                                          tombstone_ratio=None,
                                          max_age_s=5.0), clock=clock)
    assert comp.due() is None  # empty delta has no age
    m.upsert(data[:1] + 0.5)
    clock.advance(4.9)
    assert comp.due() is None
    clock.advance(0.2)
    assert comp.due() == "age"
    # a Compactor WITHOUT an explicit clock inherits the mutable's — two
    # different time bases would silently disarm max_age_s
    comp2 = stream.Compactor(
        m, policy=stream.CompactionPolicy(delta_fill=None,
                                          tombstone_ratio=None,
                                          max_age_s=5.0))
    assert comp2.due() == "age"
    rep = comp.run_once()
    assert rep["trigger"] == "age" and m.stats()["delta_rows"] == 0
    assert comp.due() is None  # the fold reset the age base


def test_compactor_tombstone_watermark_rebuilds(data):
    clock = FakeClock()
    m = wrap_bf(data, delta_capacity=16, clock=clock)
    comp = stream.Compactor(
        m, policy=stream.CompactionPolicy(delta_fill=None,
                                          tombstone_ratio=0.25), clock=clock)
    m.delete(np.arange(len(data) // 4 + 1))
    assert comp.due() == "tombstone_ratio"
    rep = comp.run_once()
    assert rep["mode"] == "rebuild" and rep["reclaimed"] == len(data) // 4 + 1
    assert m.stats()["sealed_dead"] == 0 and comp.due() is None


def test_compactor_forced_run(data):
    clock = FakeClock()
    m = wrap_bf(data, delta_capacity=16, clock=clock)
    m.upsert(data[:2] + 0.5)
    comp = stream.Compactor(
        m, policy=stream.CompactionPolicy(delta_fill=None,
                                          tombstone_ratio=None), clock=clock)
    assert comp.due() is None
    rep = comp.run_once(force=True)
    assert rep["trigger"] == "forced" and rep["folded"] == 2


def test_compactor_background_thread_liveness(data):
    """Liveness of the real poll loop: a due watermark is picked up without
    any run_once() call. Bounded by a poll deadline, not a timed sleep."""
    import time as _time

    m = wrap_bf(data, delta_capacity=16)
    comp = stream.Compactor(
        m, policy=stream.CompactionPolicy(delta_fill=0.5),
        poll_interval_s=0.01).start()
    try:
        m.upsert(data[:8] + 0.5)
        deadline = _time.monotonic() + 30.0
        while m.stats()["epoch"] == 0 and _time.monotonic() < deadline:
            _time.sleep(0.01)
        assert m.stats()["epoch"] >= 1, "background compactor never fired"
    finally:
        comp.close()


# -- serve integration --------------------------------------------------------

def test_service_write_path_read_your_writes(data, queries):
    clock = FakeClock()
    m = wrap_bf(data, delta_capacity=16, clock=clock)
    svc = SearchService(max_batch=4, clock=clock, start_workers=False)
    svc.publish("m", m, k=5)
    g = svc.upsert("m", queries[0:1] + 1e-3)
    fut = svc.submit("m", queries[:1], 5)
    clock.advance(1.0)
    assert svc.pump() == 1
    _, ids = fut.result(timeout=0)
    assert int(np.asarray(ids)[0, 0]) == int(g[0])  # read-your-writes
    assert svc.delete("m", g) == 1
    fut = svc.submit("m", queries[:1], 5)
    clock.advance(1.0)
    svc.pump()
    assert int(g[0]) not in np.asarray(fut.result(timeout=0)[1])[0]
    # taxonomy: non-mutable names have no write path; closed service fails
    bf2 = brute_force.BruteForce().build(jnp.asarray(data))
    svc.publish("frozen", bf2, k=5, warm=False)
    with pytest.raises(RaftError, match="not a mutable"):
        svc.upsert("frozen", queries[:1])
    svc.shutdown()
    with pytest.raises(ServiceClosedError):
        svc.upsert("m", queries[:1])


def test_republish_plain_index_closes_write_path(data, queries):
    """Republishing a NON-mutable index under a formerly-mutable name must
    close the write path — otherwise upserts would route to an index nobody
    serves (silently lost writes). A hook republish (what the compactor
    publishes after a swap) keeps it open."""
    clock = FakeClock()
    m = wrap_bf(data, delta_capacity=16, clock=clock)
    svc = SearchService(max_batch=4, clock=clock, start_workers=False)
    svc.publish("m", m, k=5)
    svc.upsert("m", queries[:1])
    svc.publish("m", m.searcher(), k=5)  # compactor-style hook republish
    svc.upsert("m", queries[1:2])  # write path survives (marked hook)
    bf2 = brute_force.BruteForce().build(jnp.asarray(data))
    # an UNMARKED bare hook takes the name: writes must stop routing to the
    # orphaned mutable (they would vanish — nobody serves it)
    svc.publish("m", brute_force.batched_searcher(bf2), k=5, warm=False)
    with pytest.raises(RaftError, match="not a mutable"):
        svc.upsert("m", queries[:1])
    svc.publish("m", m, k=5, warm=False)  # mutable republish reopens it
    svc.upsert("m", queries[:1])
    svc.publish("m", bf2, k=5, warm=False)  # plain index closes it again
    with pytest.raises(RaftError, match="not a mutable"):
        svc.upsert("m", queries[:1])
    svc.shutdown()


def test_load_rearms_age_watermark(data, tmp_path):
    """A restored non-empty delta has lost its write timestamps; load must
    re-base the age from load time so max_age_s still fires."""
    clock = FakeClock()
    m = wrap_bf(data, delta_capacity=16, clock=clock)
    m.upsert(data[:2] + 0.5)
    p = str(tmp_path / "m.stream")
    stream.save(m, p)
    clock2 = FakeClock()
    m2 = stream.load(p, clock=clock2)
    comp = stream.Compactor(
        m2, policy=stream.CompactionPolicy(delta_fill=None,
                                           tombstone_ratio=None,
                                           max_age_s=5.0), clock=clock2)
    assert comp.due() is None
    clock2.advance(5.1)
    assert comp.due() == "age"
    assert comp.run_once()["folded"] == 2


def test_service_delta_full_is_overload(data):
    clock = FakeClock()
    m = wrap_bf(data, delta_capacity=8, clock=clock)
    svc = SearchService(max_batch=4, clock=clock, start_workers=False)
    svc.publish("m", m, k=5)
    svc.upsert("m", data[:8] + 0.5)
    with pytest.raises(OverloadedError):
        svc.upsert("m", data[:1])
    svc.shutdown()


def test_publish_mutable_refuses_search_params(data):
    m = wrap_bf(data)
    reg = IndexRegistry(buckets=(1,))
    with pytest.raises(RaftError, match="wrap time"):
        reg.publish("m", m, search_params=object(), warm=False)


def test_registry_lease_pins_pre_compaction_epoch(data, queries):
    """The hot-swap contract: a lease taken before a compaction swap keeps
    serving the pinned (frozen) pre-compaction epoch; the published
    successor serves the folded state."""
    m = wrap_bf(data, delta_capacity=16)
    reg = IndexRegistry(buckets=(4,))
    reg.publish("m", m, k=5)
    g = m.upsert(queries[0:1] + 1e-3)
    with reg.lease("m") as v_old:
        m.compact()
        reg.publish("m", m.searcher(), k=5)
        # the leased (old-epoch) searcher still works mid-swap, serving the
        # frozen pre-compaction view — the upsert is in its delta
        _, ids = v_old.searcher(jnp.asarray(queries[:4]), 5)
        assert int(np.asarray(ids)[0, 0]) == int(g[0])
    assert reg.live_versions("m") == (2,)
    with reg.lease("m") as v_new:
        _, ids = v_new.searcher(jnp.asarray(queries[:4]), 5)
        assert int(np.asarray(ids)[0, 0]) == int(g[0])  # folded, still live


def test_compaction_swap_under_load_loses_nothing(data, queries):
    """The acceptance-critical property: compaction swaps landing mid-load
    (writes + reads in flight) fail zero requests and lose zero writes."""
    idx = ivf_flat.build(ivf_flat.IndexParams(n_lists=8, seed=0),
                         jnp.asarray(data))
    m = stream.MutableIndex(idx, search_params=ivf_flat.SearchParams(n_probes=8),
                            delta_capacity=64, retain_vectors=False,
                            name="load")
    svc = SearchService(max_batch=8, max_wait_us=200.0, max_queue_rows=512)
    svc.publish("load", m, k=5)
    m.warm(svc.buckets, ks=(5,))
    comp = stream.Compactor(
        m, publisher=svc, name="load", ks=(5,),
        policy=stream.CompactionPolicy(delta_fill=0.25, tombstone_ratio=None))
    errors, done = [], []
    lock = threading.Lock()

    def reader(tid):
        for j in range(30):
            try:
                _, ids = svc.search("load", data[(tid * 31 + j) % 200:
                                                 (tid * 31 + j) % 200 + 1], 5)
                with lock:
                    done.append(int(np.asarray(ids)[0, 0]))
            except Exception as e:  # any loss is a failure
                with lock:
                    errors.append(repr(e))

    threads = [threading.Thread(target=reader, args=(t,)) for t in range(4)]
    for t in threads:
        t.start()
    # writer + compactor on this thread: two full fold cycles mid-load
    swaps = 0
    for step in range(40):
        svc.upsert("load", data[step % 100:step % 100 + 2] + 0.5)
        if comp.due():
            comp.run_once()
            swaps += 1
    for t in threads:
        t.join(60)
        assert not t.is_alive(), "reader wedged"
    svc.shutdown()
    assert errors == []
    assert len(done) == 120
    assert swaps >= 2 and m.stats()["epoch"] == swaps


def test_warm_delta_ladder_keeps_hot_path_compile_free(data, queries):
    """The shape discipline the delta bucket ladder exists for: after
    warm(), searches at EVERY delta fill level (and the writes between
    them) trigger zero compiles — asserted via obs compile attribution."""
    import jax

    from raft_tpu.obs import compile as obs_compile

    if not obs_compile.install():  # pragma: no cover - ancient jax
        pytest.skip("jax.monitoring unavailable")
    clock = FakeClock()
    m = wrap_bf(data, delta_capacity=32, clock=clock)
    svc = SearchService(max_batch=4, clock=clock, start_workers=False)
    svc.publish("m", m, k=5)
    rep = m.warm(svc.buckets, ks=(5,))
    assert sorted(rep[5]) == [1, 2, 4]
    with obs_compile.attribution() as rec:
        for step in range(33):  # walks the delta through buckets 8..32
            if step:
                m.upsert(data[step:step + 1] + 0.5)
            fut = svc.submit("m", queries[:2], 5)
            clock.advance(1.0)
            svc.pump()
            fut.result(timeout=0)
    assert rec.compile_s == 0.0 and rec.programs == 0


def test_rebuild_uses_injected_builder(data, queries):
    """builder= (ISSUE 6) replaces the default module.build in REBUILD
    compaction — the hook the sharded CAGRA rebuild rides
    (parallel.cagra.merged_builder). For IVF kinds a builder also satisfies
    can_rebuild without index_params."""
    from raft_tpu.neighbors import ivf_flat

    calls = []

    def builder(rows, res=None):
        calls.append(rows.shape[0])
        return ivf_flat.build(ivf_flat.IndexParams(n_lists=4, seed=0), rows)

    idx = ivf_flat.build(ivf_flat.IndexParams(n_lists=4, seed=0),
                         jnp.asarray(data))
    m = stream.MutableIndex(idx, search_params=ivf_flat.SearchParams(n_probes=4),
                            delta_capacity=32, dataset=data, builder=builder)
    assert m.can_rebuild  # builder stands in for index_params
    gids = m.upsert(queries[0:1] + 1e-3)
    m.delete([0, 1])
    rep = m.compact(mode="rebuild")
    assert rep["mode"] == "rebuild" and rep["reclaimed"] == 2
    assert calls == [len(data) - 2 + 1]  # the live-row matrix, once
    # the rebuilt sealed serves: parity vs ground truth over the live rows
    live_mat = np.concatenate([data[2:], np.asarray(queries[0:1] + 1e-3)])
    live_gids = np.concatenate([np.arange(2, len(data)), gids])
    want = bf_gids(live_mat, live_gids, queries, 5)
    _, got = m.search(queries, 5)
    np.testing.assert_array_equal(np.asarray(got), want)


def test_builder_kind_mismatch_rejected(data):
    """A builder returning a different index kind is a configuration error,
    caught at the swap — not a silently corrupted mutable index."""
    from raft_tpu.neighbors import ivf_flat

    def wrong_builder(rows, res=None):
        return brute_force.BruteForce().build(jnp.asarray(rows))

    idx = ivf_flat.build(ivf_flat.IndexParams(n_lists=4, seed=0),
                         jnp.asarray(data))
    m = stream.MutableIndex(idx, search_params=ivf_flat.SearchParams(n_probes=4),
                            delta_capacity=32, dataset=data,
                            builder=wrong_builder)
    m.upsert(data[:1] + 1e-3)
    with pytest.raises(RaftError, match="builder returned"):
        m.compact(mode="rebuild")
