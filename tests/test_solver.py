"""MST / connect_components / single-linkage tests.

References: scipy.sparse.csgraph.minimum_spanning_tree for MST weight
parity, scipy.cluster.hierarchy single linkage for HAC parity — the same
trusted-host-result strategy as the reference's SOLVERS_TEST / CLUSTER_TEST
gtests (SURVEY.md §4).
"""

import numpy as np
import pytest
import scipy.sparse as sps
import scipy.sparse.csgraph as csgraph
from scipy.cluster.hierarchy import fcluster, linkage

import jax.numpy as jnp

from raft_tpu import sparse
from raft_tpu.cluster import single_linkage
from raft_tpu.solver import mst


def _random_graph(rng, n, density=0.3, connected=True):
    a = sps.random(n, n, density=density, random_state=np.random.RandomState(rng.integers(1 << 30)), format="csr", dtype=np.float32)
    a.data = np.abs(a.data) + 0.01
    a = (a + a.T) / 2  # symmetric
    if connected:
        # add a ring to guarantee connectivity
        ring = sps.csr_matrix(
            (np.full(n, 0.5, np.float32), (np.arange(n), (np.arange(n) + 1) % n)), shape=(n, n)
        )
        a = (a + ring + ring.T).tocsr()
    a.setdiag(0)
    a.eliminate_zeros()
    return a.tocsr()


class TestMst:
    @pytest.mark.parametrize("n", [8, 30, 64])
    def test_weight_matches_scipy(self, rng, n):
        a = _random_graph(rng, n)
        out = mst(sparse.from_scipy(a, cap=a.nnz + 5))
        expect = csgraph.minimum_spanning_tree(a).sum()
        ne = int(out.n_edges)
        assert ne == n - 1
        got = float(np.asarray(out.weights[:ne]).sum())
        np.testing.assert_allclose(got, expect, rtol=1e-5)

    def test_forest_on_disconnected(self, rng):
        # two separate cliques => spanning forest with n-2 edges, 2 colors
        n = 12
        half = n // 2
        d = np.zeros((n, n), np.float32)
        d[:half, :half] = 1.0
        d[half:, half:] = 2.0
        np.fill_diagonal(d, 0.0)
        csr = sparse.dense_to_csr(jnp.asarray(d))
        out = mst(csr)
        assert int(out.n_edges) == n - 2
        colors = np.asarray(out.colors)
        assert len(np.unique(colors)) == 2
        assert len(np.unique(colors[:half])) == 1

    def test_sorted_output(self, rng):
        a = _random_graph(rng, 20)
        out = mst(sparse.from_scipy(a))
        ne = int(out.n_edges)
        w = np.asarray(out.weights[:ne])
        assert (np.diff(w) >= -1e-7).all()


class TestConnectComponents:
    def test_connects_two_blobs(self, rng):
        x = np.concatenate([
            rng.normal(0, 0.1, (10, 3)), rng.normal(5, 0.1, (8, 3))
        ]).astype(np.float32)
        colors = np.concatenate([np.zeros(10, np.int32), np.ones(8, np.int32)])
        out = sparse.connect_components(jnp.asarray(x), jnp.asarray(colors))
        ne = int(out.nnz)
        assert ne >= 1
        rows = np.asarray(out.rows[:ne])
        cols = np.asarray(out.cols[:ne])
        # every edge crosses the components
        assert (colors[rows] != colors[cols]).all()


class TestSingleLinkage:
    @pytest.mark.parametrize("connectivity", ["pairwise", "knn"])
    def test_matches_scipy_blobs(self, rng, connectivity):
        # well-separated blobs: single-linkage must recover them exactly
        centers = np.array([[0, 0], [10, 0], [0, 10]], np.float32)
        x = np.concatenate([
            rng.normal(c, 0.3, (20, 2)).astype(np.float32) for c in centers
        ])
        out = single_linkage(jnp.asarray(x), n_clusters=3, connectivity=connectivity, n_neighbors=5)
        labels = np.asarray(out.labels)
        expect = fcluster(linkage(x, method="single"), 3, criterion="maxclust")
        # label sets must induce the same partition
        for c in range(3):
            members = labels == c
            assert len(np.unique(expect[members])) == 1
        assert len(np.unique(labels)) == 3

    def test_dendrogram_deltas_match_scipy(self, rng):
        x = rng.random((25, 4)).astype(np.float32)
        out = single_linkage(jnp.asarray(x), n_clusters=1, connectivity="pairwise", metric="euclidean")
        expect = linkage(x, method="single", metric="euclidean")
        np.testing.assert_allclose(np.sort(out.deltas), np.sort(expect[:, 2]), rtol=1e-4)

    def test_knn_euclidean_deltas_match_scipy(self, rng):
        # random data: kNN membership is asymmetric, so this regresses the
        # canonicalize-before-mst edge retention (i in knn(j) but not vice versa)
        x = rng.random((40, 3)).astype(np.float32)
        out = single_linkage(jnp.asarray(x), n_clusters=1, connectivity="knn",
                             n_neighbors=15, metric="euclidean")
        expect = linkage(x, method="single", metric="euclidean")
        np.testing.assert_allclose(np.sort(out.deltas), np.sort(expect[:, 2]), rtol=1e-4)

    def test_knn_repairs_disconnected_graph(self, rng):
        # blobs far apart with tiny k: knn graph is disconnected; fixup must
        # still produce a full tree and correct labels
        x = np.concatenate([
            rng.normal(0, 0.05, (15, 2)), rng.normal(100, 0.05, (15, 2))
        ]).astype(np.float32)
        out = single_linkage(jnp.asarray(x), n_clusters=2, connectivity="knn", n_neighbors=3)
        labels = np.asarray(out.labels)
        assert len(np.unique(labels[:15])) == 1
        assert len(np.unique(labels[15:])) == 1
        assert labels[0] != labels[15]
