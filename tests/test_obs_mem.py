"""Memory ledger & capacity observability (ISSUE 10, tier-1 ``mem`` marker).

Covers: ledger semantics (account/release/reaccount, weakref auto-release,
peaks, disabled mode), the retirement audits over the serving stack's
correctness-critical free paths (registry retire-after-drain, compaction
swap, sharded staggered fold, ``parallel.release_programs`` — the PR 9
leak class as first-class tests), the footprint estimator's ±20% accuracy
contract at 100k rows for all four index kinds, the
``memory_budget_bytes`` admission gate (whole-or-nothing at
build/publish/upsert), ``/debug/mem`` routing, the
``Resources.workspace_bytes`` attribution pin, and the disabled-mode
overhead smoke.

Deterministic: injected clocks where time matters, ``gc.collect()`` where
liveness matters — no wall sleeps in assertions. Ledger assertions are
RELATIVE (baseline-subtracted) and name-scoped: the ledger is a process
singleton, and other tests' live indexes legitimately appear in it.
"""

import gc
import json
import threading
import urllib.request
import weakref

import numpy as np
import pytest

from raft_tpu import obs
from raft_tpu.core import Resources
from raft_tpu.obs import mem as obs_mem
from raft_tpu.serve.errors import MemoryBudgetError, OverloadedError

pytestmark = pytest.mark.mem


def _dev_total():
    gc.collect()
    return obs_mem.totals()["device_bytes"]


def _entries(name=None, component=None):
    return [r for r in obs_mem.breakdown()
            if (name is None or r["name"] == name)
            and (component is None or r["component"] == component)]


# ---------------------------------------------------------------------------
# ledger semantics
# ---------------------------------------------------------------------------

class TestLedger:
    def test_account_release_totals_and_gauges(self):
        led = obs_mem.MemLedger()
        t = led.account("c1", name="n1", device_bytes=100, host_bytes=10)
        t2 = led.account("c1", name="n2", device_bytes=50)
        tot = led.totals()
        assert tot["device_bytes"] == 150 and tot["host_bytes"] == 10
        led.release(t)
        led.release(t)  # idempotent
        tot = led.totals()
        assert tot["device_bytes"] == 50 and tot["host_bytes"] == 0
        assert tot["device_peak_bytes"] == 150  # peak survives the release
        led.release(t2)
        assert led.totals()["allocations"] == 0

    def test_array_nbytes_and_reaccount(self):
        led = obs_mem.MemLedger()
        a = np.zeros((8, 4), np.float32)
        t = led.account("c", device=[a], host=a)
        assert led.totals() == {"device_bytes": 128, "host_bytes": 128,
                                "device_peak_bytes": 128,
                                "host_peak_bytes": 128, "allocations": 1}
        led.reaccount(t, device=[a, a], epoch=3)
        assert led.totals()["device_bytes"] == 256
        assert led.totals()["host_bytes"] == 0
        assert led.breakdown()[0]["epoch"] == 3
        led.reset_peak()
        assert led.totals()["device_peak_bytes"] == 256

    def test_owner_weakref_autorelease(self):
        led = obs_mem.MemLedger()

        class Owner:
            pass

        o = Owner()
        led.account("c", device_bytes=64, owner=o)
        assert led.totals()["device_bytes"] == 64
        del o
        gc.collect()
        assert led.totals()["device_bytes"] == 0

    def test_owner_idempotency_replaces(self):
        led = obs_mem.MemLedger()

        class Owner:
            pass

        o = Owner()
        led.account("c", name="a", device_bytes=64, owner=o)
        led.account("c", name="b", device_bytes=32, owner=o)
        # release-then-insert: a replacement never double-counts, so the
        # peak stays at the larger single entry
        assert led.totals() == {"device_bytes": 32, "host_bytes": 0,
                                "device_peak_bytes": 64,
                                "host_peak_bytes": 0, "allocations": 1}
        assert led.breakdown()[0]["name"] == "b"
        # a DIFFERENT component for the same owner is a separate entry
        led.account("c2", device_bytes=8, owner=o)
        assert led.totals()["allocations"] == 2
        del o
        gc.collect()
        assert led.totals()["allocations"] == 0

    def test_retire_then_audit(self):
        clock_now = [0.0]
        led = obs_mem.MemLedger(clock=lambda: clock_now[0])

        class Owner:
            pass

        o = Owner()
        t = led.account("c", name="x", device_bytes=64, owner=o)
        led.retire(t)
        clock_now[0] = 5.0
        aud = led.audit()
        assert not aud["clean"]
        assert aud["retired_unfreed"][0]["retired_for_s"] == 5.0
        assert aud["retired_unfreed"][0]["name"] == "x"
        del o
        gc.collect()
        aud = led.audit()
        assert aud["clean"] and led.totals()["device_bytes"] == 0

    def test_disabled_mode_noops(self):
        led = obs_mem.MemLedger()
        obs.disable()
        try:
            t = led.account("c", device_bytes=64)
            assert t is None
            led.reaccount(t, device_bytes=1)  # None token no-ops
            led.retire(t)
            led.release(t)
            assert led.totals()["device_bytes"] == 0
        finally:
            obs.enable()


# ---------------------------------------------------------------------------
# retirement audits over the real free paths
# ---------------------------------------------------------------------------

def _small_flat(rng, n=512, d=8, n_lists=8, seed=0):
    from raft_tpu.neighbors import ivf_flat

    x = rng.random((n, d)).astype(np.float32)
    return ivf_flat.build(
        ivf_flat.IndexParams(n_lists=n_lists, kmeans_n_iters=2, seed=seed), x)


class TestRetirementAudit:
    def test_registry_retire_after_drain_frees_bytes(self, rng):
        """THE acceptance audit: a published-then-retired serve version's
        accounted device bytes return to the pre-publish baseline —
        weakref-verified (release only ever happens through the owner
        weakref), injected clock, no wall sleeps."""
        from raft_tpu.serve import IndexRegistry

        clock_now = [0.0]
        baseline = _dev_total()
        reg = IndexRegistry(buckets=(1, 4), clock=lambda: clock_now[0])
        idx1 = _small_flat(rng, seed=1)
        reg.publish("aud1", idx1, k=3)
        v1_bytes = _dev_total() - baseline
        assert v1_bytes > 0, "the build must be accounted"
        wr_idx = weakref.ref(idx1)
        del idx1  # the registry version now holds the only reference
        assert _dev_total() - baseline == v1_bytes  # published = pinned

        # hold a lease (an in-flight flush) across the swap: v1 must NOT
        # free while draining
        with reg.lease("aud1") as v1:
            clock_now[0] = 1.0
            idx2 = _small_flat(rng, seed=2)
            reg.publish("aud1", idx2, k=3)
            gc.collect()
            assert wr_idx() is not None, "leased version freed early"
            assert not obs_mem.audit()["clean"] or v1.leases >= 0
        # lease drained → retire-after-drain ran → v1's bytes free
        del v1
        gc.collect()
        v2_bytes = int(sum(x.nbytes for x in idx2.tree_flatten()[0]))
        assert wr_idx() is None, "retired version still pinned after drain"
        assert _dev_total() - baseline == v2_bytes, (
            "retired version's device bytes did not return to the "
            "pre-publish baseline")
        assert obs_mem.audit(collect=True)["clean"]

    def test_pinned_searcher_shows_as_leak(self, rng):
        """Negative control — the PR 9 class: something (here a deliberate
        strong ref, there the ProgramCache) pins a retired version's
        searcher; the audit must SEE it, and see it clear."""
        from raft_tpu.serve import IndexRegistry

        reg = IndexRegistry(buckets=(1, 4))
        reg.publish("aud2", _small_flat(rng, seed=3), k=3)
        pin = reg.active("aud2").searcher  # the leak: a strong reference
        reg.publish("aud2", _small_flat(rng, seed=4), k=3)
        aud = obs_mem.audit(collect=True)
        leaks = [r for r in aud["retired_unfreed"]
                 if r["component"] == "serve/version" and r["name"] == "aud2"]
        assert leaks, "a pinned retired searcher must surface in the audit"
        del pin
        aud = obs_mem.audit(collect=True)
        assert not [r for r in aud["retired_unfreed"]
                    if r["component"] == "serve/version"
                    and r["name"] == "aud2"]

    def test_compact_swap_frees_pre_epoch(self, rng):
        """MutableIndex.compact(): the pre-swap epoch's stream arrays and
        replaced sealed store free once the last pinned hook drops —
        accounted bytes return to exactly the live state's entries."""
        from raft_tpu.neighbors import brute_force
        from raft_tpu.stream import MutableIndex

        baseline = _dev_total()
        bf = brute_force.BruteForce().build(
            rng.random((64, 8)).astype(np.float32))
        m = MutableIndex(bf, delta_capacity=32, name="aud3",
                         clock=lambda: 0.0)
        del bf  # the mutable owns the sealed index now
        m.upsert(rng.random((20, 8)).astype(np.float32))
        hook = m.searcher()  # a lease-pinned epoch-0 hook
        m.compact(mode="rebuild")
        aud = obs_mem.audit(collect=True)
        assert [r for r in aud["retired_unfreed"] if r["name"] == "aud3"], (
            "pinned pre-compaction epoch must show in the audit")
        del hook
        aud = obs_mem.audit(collect=True)
        assert not [r for r in aud["retired_unfreed"]
                    if r["name"] == "aud3"]
        # totals == exactly the live entries (old epoch fully gone)
        live = sum(r["device_bytes"] for r in _entries(name="aud3"))
        assert _dev_total() - baseline == live
        epochs = {(r["component"], r["epoch"])
                  for r in _entries(name="aud3")}
        assert epochs == {("stream", 1), ("index/brute_force", 1)}
        del m
        gc.collect()
        assert _dev_total() - baseline == 0

    def test_sharded_fold_frees_one_shard(self, rng):
        """ShardedMutableIndex staggered fold: only the folded shard's
        epoch advances; its pre-fold entries free; the sibling shard's
        entries are untouched; shard attribution rides the ledger."""
        from raft_tpu.neighbors import brute_force
        from raft_tpu.stream import ShardedMutableIndex

        baseline = _dev_total()
        x = rng.random((96, 8)).astype(np.float32)
        sm = ShardedMutableIndex(
            x, n_shards=2, delta_capacity=32, name="aud4",
            build=lambda rows: brute_force.BruteForce().build(rows),
            clock=lambda: 0.0)
        sm.upsert(rng.random((16, 8)).astype(np.float32))
        shards = {r["shard"] for r in _entries(component="stream")
                  if r["name"].startswith("aud4/")}
        assert shards == {0, 1}, "per-shard ledger attribution missing"
        report = sm.compact(mode="rebuild")
        folded = report["shard"]
        gc.collect()
        assert obs_mem.audit(collect=True)["clean"]
        for s in range(2):
            eps = {r["epoch"] for r in _entries(name=f"aud4/shard{s}",
                                                component="stream")}
            assert eps == ({1} if s == folded else {0}), (s, folded, eps)
        live = sum(r["device_bytes"] for r in obs_mem.breakdown()
                   if r["name"].startswith("aud4/"))
        assert _dev_total() - baseline == live
        del sm
        gc.collect()
        assert _dev_total() - baseline == 0

    def test_release_programs_frees_accounted_comms(self, rng):
        """parallel.release_programs as a ledger-audited free path: an
        allocation owned by a retired Comms frees only after the program
        cache releases it — accounted bytes return to the pre-op
        baseline (the PR 9 fix, generalized into the audit)."""
        import jax
        from jax.sharding import Mesh

        from raft_tpu import parallel
        from raft_tpu.comms import Comms

        baseline = _dev_total()
        x = rng.random((64, 8)).astype(np.float32)
        q = rng.random((4, 8)).astype(np.float32)
        c = Comms(Mesh(np.array(jax.devices()[:2]), ("data",)), "data")
        d, i = parallel.knn.knn(c, x, q, k=3)
        c.sync_stream(d, i)
        # attribute the mesh's working set to the communicator: the entry
        # must live exactly as long as the comms does
        tok = obs_mem.account("comms", name="aud5", device=[d, i], owner=c)
        pinned = _dev_total() - baseline
        assert pinned > 0
        obs_mem.retire(tok)
        ref = weakref.ref(c)
        del d, i, c
        gc.collect()
        assert ref() is not None, "sanity: the program cache pins the comms"
        aud = obs_mem.audit(collect=True)
        assert [r for r in aud["retired_unfreed"] if r["name"] == "aud5"], (
            "the cache-pinned comms must surface in the audit")
        parallel.release_programs(ref())
        gc.collect()
        assert ref() is None
        assert _dev_total() - baseline == 0, (
            "accounted bytes did not return to the pre-op baseline")
        assert not [r for r in obs_mem.audit()["retired_unfreed"]
                    if r["name"] == "aud5"]


# ---------------------------------------------------------------------------
# footprint estimator accuracy (acceptance: ±20% at 100k, tier-1)
# ---------------------------------------------------------------------------

def _measured_index_bytes(index):
    kind, leaves = obs_mem._index_kind_and_leaves(index)
    assert kind is not None
    return int(sum(x.nbytes for x in leaves))


def _plan_params(d):
    """Per-kind build params sized so tier-1 stays CPU-cheap while the
    arrays being estimated stay 100k-scale."""
    from raft_tpu.neighbors import cagra, ivf_flat, ivf_pq

    return {
        "brute_force": None,
        "ivf_flat": ivf_flat.IndexParams(n_lists=256, kmeans_n_iters=4),
        "ivf_pq": ivf_pq.IndexParams(n_lists=256, pq_bits=4,
                                     pq_dim=max(d // 2, 1),
                                     kmeans_n_iters=4),
        "cagra": cagra.IndexParams(intermediate_graph_degree=32,
                                   graph_degree=16, build_n_probes=8),
    }


def _build_kind(kind, params, x):
    from raft_tpu.neighbors import brute_force, cagra, ivf_flat, ivf_pq

    if kind == "brute_force":
        return brute_force.BruteForce().build(x)
    mod = {"ivf_flat": ivf_flat, "ivf_pq": ivf_pq, "cagra": cagra}[kind]
    return mod.build(params, x)


def _assert_plan_brackets(kind, params, idx, n, d):
    measured = _measured_index_bytes(idx)
    est = obs_mem.plan(kind, params, n, d)["index_bytes"]
    assert abs(est - measured) <= 0.20 * measured, (
        f"{kind}: plan {est} vs measured {measured} "
        f"({est / measured:.3f}x) outside the ±20% contract")


@pytest.mark.parametrize("kind", ["brute_force", "ivf_flat", "ivf_pq"])
def test_plan_within_20pct_at_100k(rng, kind):
    """obs.mem.plan() vs the measured ledger at 100k rows (the ISSUE 10
    accuracy bar; CAGRA's case is split below, the 1M cases ride the slow
    manifest). Real builds at a CPU-cheap dim — the IVF padded-list
    capacity model is the part with real slack."""
    import jax

    n, d = 100_000, 16
    params = _plan_params(d)[kind]
    idx = _build_kind(kind, params, rng.random((n, d)).astype(np.float32))
    jax.block_until_ready(jax.tree_util.tree_leaves(
        idx if kind != "brute_force" else idx.dataset))
    _assert_plan_brackets(kind, params, idx, n, d)


def test_plan_cagra_within_20pct_at_100k(rng):
    """The CAGRA leg of the 100k accuracy bar. A CagraIndex's allocation
    is SHAPE-exact — dataset (n, d) + graph (n, graph_degree) int32; the
    knn-graph self-search that fills the graph runs minutes on the CPU
    mesh and cannot change a byte of it. So tier-1 runs the real build
    at 4k (pinning that the pipeline's output matches the plan exactly)
    and measures the 100k LAYOUT through the same ledger hook; the full
    100k build rides the slow manifest."""
    import jax

    from raft_tpu.neighbors import cagra

    d = 16
    params = _plan_params(d)["cagra"]
    small = _build_kind("cagra", params,
                        rng.random((4096, d)).astype(np.float32))
    jax.block_until_ready(small.graph)
    est_small = obs_mem.plan("cagra", params, 4096, d)["index_bytes"]
    assert est_small == _measured_index_bytes(small), (
        "cagra plan must be exact against the real build pipeline")

    n = 100_000
    idx = cagra.CagraIndex(
        dataset=jax.numpy.asarray(rng.random((n, d)).astype(np.float32)),
        graph=jax.numpy.zeros((n, params.graph_degree), jax.numpy.int32))
    tok = obs_mem.account_index(idx, name="plan_cagra_100k")
    try:
        _assert_plan_brackets("cagra", params, idx, n, d)
        entry = [r for r in _entries(name="plan_cagra_100k")][0]
        assert entry["device_bytes"] == _measured_index_bytes(idx)
    finally:
        obs_mem.release(tok)


def test_plan_fast_scan_tier_within_20pct_at_100k(rng):
    """ISSUE 16 satellite: plan() prices the fast-scan signature tier
    (list_sig + sig_scales) inside the same ±20% contract — the packed
    tier rides the padded-list capacity model, so its per-array slack is
    the same slack as list_codes, and the decode scales are exact."""
    import dataclasses

    import jax

    n, d = 100_000, 16
    base = _plan_params(d)["ivf_pq"]
    params = dataclasses.replace(base, fast_scan="1bit")
    idx = _build_kind("ivf_pq", params,
                      rng.random((n, d)).astype(np.float32))
    jax.block_until_ready(jax.tree_util.tree_leaves(idx))
    assert idx.has_fast_scan
    _assert_plan_brackets("ivf_pq", params, idx, n, d)
    with_tier = obs_mem.plan("ivf_pq", params, n, d)["breakdown"]
    without = obs_mem.plan("ivf_pq", base, n, d)["breakdown"]
    sig = int(np.asarray(idx.list_sig).nbytes)
    assert abs(with_tier["list_sig"] - sig) <= 0.20 * sig, (
        with_tier["list_sig"], sig)
    assert with_tier["sig_scales"] == int(np.asarray(idx.sig_scales).nbytes)
    assert set(with_tier) - set(without) == {"list_sig", "sig_scales"}


@pytest.mark.slow
def test_plan_cagra_full_build_at_100k(rng):
    """The full 100k CAGRA build vs the plan (slow manifest — the
    self-search is minutes on the CPU mesh)."""
    import jax

    n, d = 100_000, 16
    params = _plan_params(d)["cagra"]
    idx = _build_kind("cagra", params, rng.random((n, d)).astype(np.float32))
    jax.block_until_ready(idx.graph)
    _assert_plan_brackets("cagra", params, idx, n, d)


@pytest.mark.slow
@pytest.mark.parametrize("kind", ["brute_force", "ivf_flat", "ivf_pq"])
def test_plan_within_20pct_at_1m(rng, kind):
    """The 1M-row estimator case (slow manifest): the IVF padded-list
    models at the scale ROADMAP 2's tiering planning actually targets."""
    import jax

    n, d = 1_000_000, 16
    params = _plan_params(d)[kind]
    idx = _build_kind(kind, params, rng.random((n, d)).astype(np.float32))
    jax.block_until_ready(jax.tree_util.tree_leaves(
        idx if kind != "brute_force" else idx.dataset))
    _assert_plan_brackets(kind, params, idx, n, d)


def test_plan_breakdown_and_unknown_kind():
    from raft_tpu.core.errors import RaftError

    p = obs_mem.plan("brute_force", None, 1000, 32)
    assert p["index_bytes"] == 1000 * 32 * 4 == p["breakdown"]["dataset"]
    assert p["build_peak_bytes"] >= p["index_bytes"]
    assert obs_mem.plan("brute_force", None, 1000, 32,
                        dtype="int8")["index_bytes"] == 1000 * 32
    with pytest.raises(RaftError):
        obs_mem.plan("nope", None, 10, 10)


# ---------------------------------------------------------------------------
# memory_budget_bytes admission gate (whole-or-nothing)
# ---------------------------------------------------------------------------

class TestBudgetGate:
    def test_build_refused_before_any_work(self, rng):
        from raft_tpu.neighbors import ivf_flat

        x = rng.random((512, 8)).astype(np.float32)
        res = Resources(memory_budget_bytes=16)
        with pytest.raises(MemoryBudgetError) as ei:
            ivf_flat.build(ivf_flat.IndexParams(n_lists=8), x, res=res)
        assert ei.value.site == "build"
        assert isinstance(ei.value, OverloadedError)
        assert ei.value.budget_bytes == 16
        assert ei.value.need_bytes > 0

    def test_publish_refused_zero_partial_state(self, rng):
        """Over-budget publish: no version minted, the name stays
        unpublished, the service's write-path routing is untouched —
        the PR 9 cross-shard whole-or-nothing contract at the registry."""
        from raft_tpu.core.errors import RaftError
        from raft_tpu.serve import IndexRegistry

        reg = IndexRegistry(buckets=(1, 4))
        idx = _small_flat(rng, seed=9)
        # the index is already ledger-accounted, so the budget must sit
        # below the CURRENT totals to trip at publish (the publish-time
        # gate exists for exactly this: budgets set after builds land)
        res = Resources(memory_budget_bytes=1)
        with pytest.raises(MemoryBudgetError) as ei:
            reg.publish("gated", idx, k=3, res=res)
        assert ei.value.site == "publish"
        assert "gated" not in reg.names()
        with pytest.raises(RaftError):
            reg.active("gated")
        assert not [r for r in obs_mem.breakdown()
                    if r["component"] == "serve/version"
                    and r["name"] == "gated"]
        # and the same publish admits once the budget allows it
        reg.publish("gated", idx, k=3,
                    res=Resources(memory_budget_bytes=None))
        assert reg.active("gated").version == 1

    def test_publish_counts_unaccounted_index_bytes(self, rng):
        """An index the ledger has never seen (obs was disabled at build)
        gates on its MEASURED bytes — the gate cannot be dodged by
        building in the dark."""
        from raft_tpu.serve import IndexRegistry

        obs.disable()
        try:
            idx = _small_flat(rng, seed=10)
        finally:
            obs.enable()
        need = obs_mem.unaccounted_index_bytes(idx)
        assert need == _measured_index_bytes(idx)
        reg = IndexRegistry(buckets=(1, 4))
        used = obs_mem.totals()["device_bytes"]
        with pytest.raises(MemoryBudgetError):
            reg.publish("gated2", idx, k=3,
                        res=Resources(memory_budget_bytes=used + need - 1))

    def test_dark_published_indexes_accumulate(self, rng):
        """Review regression: an admitted dark-built (obs-disabled) index
        must JOIN the ledger at publish — otherwise a second dark publish
        gates against a total that never learned about the first and the
        budget is quietly exceeded."""
        from raft_tpu.serve import IndexRegistry

        obs.disable()
        try:
            a = _small_flat(rng, seed=20)
            b = _small_flat(rng, seed=21)
        finally:
            obs.enable()
        need = _measured_index_bytes(a)
        used = obs_mem.totals()["device_bytes"]
        res = Resources(memory_budget_bytes=used + need + need // 2)
        reg = IndexRegistry(buckets=(1, 4))
        reg.publish("dark_a", a, k=3, res=res)  # fits
        assert obs_mem.unaccounted_index_bytes(a) == 0, (
            "an admitted publish must account its index")
        with pytest.raises(MemoryBudgetError):
            reg.publish("dark_b", b, k=3, res=res)  # a's bytes now count

    def test_owner_map_pruned_on_release(self):
        """Review regression: releasing an owned entry must drop its
        owner-map key — the leak-detection module must not itself leak a
        mapping per publish→retire cycle."""

        class Owner:
            pass

        led = obs_mem.MemLedger()
        keep = Owner()
        led.account("c", device_bytes=1, owner=keep)
        for _ in range(16):
            o = Owner()
            led.account("c", device_bytes=1, owner=o)
            del o
            gc.collect()
        assert len(led._owners) == 1  # only the live owner's mapping
        assert led.totals()["allocations"] == 1

    def test_upsert_refused_nothing_written(self, rng):
        from raft_tpu.neighbors import brute_force
        from raft_tpu.stream import MutableIndex

        m = MutableIndex(
            brute_force.BruteForce().build(
                rng.random((32, 8)).astype(np.float32)),
            delta_capacity=64, name="gate_up", clock=lambda: 0.0)
        m.upsert(rng.random((7, 8)).astype(np.float32))  # bucket 8, 1 free
        before = m.stats()
        used = obs_mem.totals()["device_bytes"]
        res = Resources(memory_budget_bytes=used)  # zero headroom
        with pytest.raises(MemoryBudgetError) as ei:
            # 9 rows grow the delta bucket 8 → 16: real device growth
            m.upsert(rng.random((9, 8)).astype(np.float32), res=res)
        assert ei.value.site == "upsert"
        assert m.stats() == before, "a refused upsert wrote state"
        # a write that does NOT grow the bucket passes the same budget
        m.upsert(rng.random((1, 8)).astype(np.float32), res=res)
        assert m.stats()["delta_rows"] == 8

    def test_sharded_upsert_whole_or_nothing(self, rng):
        """Cross-shard: the summed bucket growth gates BEFORE any shard
        writes — one over-budget sibling means no shard lands a row."""
        from raft_tpu.neighbors import brute_force
        from raft_tpu.stream import ShardedMutableIndex

        x = rng.random((64, 8)).astype(np.float32)
        sm = ShardedMutableIndex(
            x, n_shards=2, delta_capacity=64, name="gate_sh",
            build=lambda rows: brute_force.BruteForce().build(rows),
            clock=lambda: 0.0)
        before = [sh.stats() for sh in sm.shards]
        used = obs_mem.totals()["device_bytes"]
        with pytest.raises(MemoryBudgetError):
            sm.upsert(rng.random((40, 8)).astype(np.float32),
                      res=Resources(memory_budget_bytes=used))
        assert [sh.stats() for sh in sm.shards] == before, (
            "a refused cross-shard upsert left partial state")

    def test_sharded_upsert_forwards_res_to_shards(self, rng):
        """Review regression: the caller's res must reach the per-shard
        upserts — a stricter ambient default budget would otherwise admit
        at the hoisted gate and refuse mid-write on shard 1, breaking
        whole-or-nothing."""
        from raft_tpu.core.resources import default_resources
        from raft_tpu.neighbors import brute_force
        from raft_tpu.stream import ShardedMutableIndex

        x = rng.random((64, 8)).astype(np.float32)
        sm = ShardedMutableIndex(
            x, n_shards=2, delta_capacity=64, name="gate_fw",
            build=lambda rows: brute_force.BruteForce().build(rows),
            clock=lambda: 0.0)
        dflt = default_resources()
        assert dflt.memory_budget_bytes is None  # suite invariant
        dflt.memory_budget_bytes = 1  # a hostile ambient budget
        try:
            out = sm.upsert(rng.random((40, 8)).astype(np.float32),
                            res=Resources(memory_budget_bytes=None))
            assert len(out) == 40
            assert sum(sh.stats()["delta_rows"] for sh in sm.shards) == 40
        finally:
            dflt.memory_budget_bytes = None

    def test_brute_force_gate_sizes_from_host_view(self, rng):
        """Review regression: the brute-force build gate prices the f32
        STORED bytes from the host view (before any device upload) — an
        f64 numpy input must not double the gate's ask."""
        from raft_tpu.neighbors import brute_force

        x64 = rng.random((256, 8))  # float64 host array
        used = obs_mem.totals()["device_bytes"]
        need_f32 = 256 * 8 * 4
        idx = brute_force.BruteForce().build(
            x64, res=Resources(memory_budget_bytes=used + need_f32))
        assert str(idx.dataset.dtype) == "float32"
        used = obs_mem.totals()["device_bytes"]  # idx is accounted now
        with pytest.raises(MemoryBudgetError):
            brute_force.BruteForce().build(
                rng.random((256, 8)),
                res=Resources(memory_budget_bytes=used + need_f32 - 1))

    def test_service_paths_carry_res(self, rng):
        """SearchService.publish/upsert thread the budget through to the
        same gates (the serve admission taxonomy end to end)."""
        from raft_tpu.neighbors import brute_force
        from raft_tpu.serve import SearchService
        from raft_tpu.stream import MutableIndex

        svc = SearchService(max_batch=4, start_workers=False,
                            clock=lambda: 0.0)
        m = MutableIndex(
            brute_force.BruteForce().build(
                rng.random((32, 8)).astype(np.float32)),
            delta_capacity=64, name="gate_svc", clock=lambda: 0.0)
        svc.publish("gate_svc", m, k=3)
        used = obs_mem.totals()["device_bytes"]
        with pytest.raises(MemoryBudgetError):
            svc.upsert("gate_svc", rng.random((9, 8)).astype(np.float32),
                       res=Resources(memory_budget_bytes=used))
        with pytest.raises(MemoryBudgetError):
            svc.publish("gate_svc2", _small_flat(rng, seed=11), k=3,
                        res=Resources(memory_budget_bytes=1))
        assert "gate_svc2" not in svc.registry.names()
        svc.shutdown()

    def test_armed_budget_requires_obs_enabled(self, rng):
        """Review regression: under obs.disable() the ledger stops
        accounting, so an armed budget would compare every admission
        against a frozen total and silently enforce nothing (three dark
        builds each see 0 used and all admit) — the gate fails loudly
        instead."""
        from raft_tpu.core.errors import RaftError
        from raft_tpu.neighbors import brute_force

        obs.disable()
        try:
            with pytest.raises(RaftError, match="disabled"):
                obs_mem.gate(Resources(memory_budget_bytes=1 << 30), 0,
                             site="publish")
            with pytest.raises(RaftError, match="disabled"):
                brute_force.BruteForce().build(
                    rng.random((32, 8)).astype(np.float32),
                    res=Resources(memory_budget_bytes=1 << 30))
            obs_mem.gate(Resources(), 0, site="publish")  # unarmed: no-op
        finally:
            obs.enable()

    def test_sharded_upsert_immune_to_concurrent_growth(self, rng,
                                                        monkeypatch):
        """Review regression: ledger growth landing between the hoisted
        cross-shard admit and shard s's write (another name's publish, a
        fold's double-buffer) must not refuse mid-write and leave a
        partial cross-shard upsert — the per-shard upserts run with the
        budget stripped, so admission is decided exactly once."""
        from raft_tpu.neighbors import brute_force
        from raft_tpu.stream import ShardedMutableIndex

        x = rng.random((64, 8)).astype(np.float32)
        sm = ShardedMutableIndex(
            x, n_shards=2, delta_capacity=64, name="gate_race",
            build=lambda rows: brute_force.BruteForce().build(rows),
            clock=lambda: 0.0)
        orig, tokens, hoisted = obs_mem.gate, [], []

        def racing_gate(res, need, **kw):
            orig(res, need, **kw)
            if not hoisted and getattr(
                    res, "memory_budget_bytes", None) is not None:
                hoisted.append(kw.get("site"))
                # the admit landed; now a "concurrent publish" eats the
                # entire remaining headroom before any shard writes
                tokens.append(obs_mem.account(
                    "test/race", name="gate_race", device_bytes=1 << 30))

        monkeypatch.setattr(obs_mem, "gate", racing_gate)
        try:
            budget = obs_mem.totals()["device_bytes"] + (1 << 30)
            out = sm.upsert(rng.random((40, 8)).astype(np.float32),
                            res=Resources(memory_budget_bytes=budget))
            assert hoisted == ["upsert"]  # the race actually fired
            assert len(out) == 40
            assert sum(sh.stats()["delta_rows"] for sh in sm.shards) == 40
        finally:
            for t in tokens:
                obs_mem.release(t)

    def test_duck_typed_mutable_without_res_kwarg(self, rng):
        """Review regression: serve resolves mutables duck-typed, so a
        custom hook whose ``upsert`` takes no ``res=`` must still write
        through ``SearchService.upsert`` — and an ARMED budget against it
        fails loudly instead of silently going unenforced."""
        from raft_tpu.core.errors import RaftError
        from raft_tpu.neighbors import brute_force
        from raft_tpu.serve import SearchService
        from raft_tpu.stream import MutableIndex

        class LegacyMutable:  # the pre-ledger duck shape
            def __init__(self, inner):
                self._inner = inner

            def searcher(self):
                return self._inner.searcher()

            def upsert(self, rows, ids=None):
                return self._inner.upsert(rows, ids)

        m = MutableIndex(
            brute_force.BruteForce().build(
                rng.random((32, 8)).astype(np.float32)),
            delta_capacity=64, name="gate_duck", clock=lambda: 0.0)
        svc = SearchService(max_batch=4, start_workers=False,
                            clock=lambda: 0.0)
        svc.publish("gate_duck", LegacyMutable(m), k=3)
        out = svc.upsert("gate_duck", rng.random((5, 8)).astype(np.float32))
        assert len(out) == 5 and m.stats()["delta_rows"] == 5
        with pytest.raises(RaftError, match="res="):
            svc.upsert("gate_duck",
                       rng.random((2, 8)).astype(np.float32),
                       res=Resources(memory_budget_bytes=1 << 40))
        svc.shutdown()


# ---------------------------------------------------------------------------
# /debug/mem endpoint + routing (404 contract preserved)
# ---------------------------------------------------------------------------

class TestDebugMemEndpoint:
    def _get(self, port, path):
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{path}", timeout=5) as r:
                return r.status, r.read().decode()
        except urllib.error.HTTPError as e:
            return e.code, e.read().decode()

    def test_debug_mem_routes_and_404_contract(self):
        exp = obs.MetricsExporter(port=0)
        try:
            code, body = self._get(exp.port, "/debug/mem")
            assert code == 200
            payload = json.loads(body)
            # "tiers" (ISSUE 15) registers once a TieredStore has lived
            # in the process — an extra registered section, not a route
            assert set(payload) - {"tiers"} == {"totals", "by_component",
                                                "top", "audit", "hbm"}
            assert payload["totals"]["device_bytes"] >= 0
            assert isinstance(payload["audit"]["retired_unfreed"], list)
            # the 404 contract survives, and names the new endpoint
            code, body = self._get(exp.port, "/debug/memx")
            assert code == 404 and "/debug/mem" in body
            code, _ = self._get(exp.port, "/metrics")
            assert code == 200
        finally:
            exp.stop()

    def test_debug_mem_reflects_ledger(self):
        t = obs_mem.account("http_probe", name="probe",
                            device_bytes=12345)
        exp = obs.MetricsExporter(port=0)
        try:
            _, body = self._get(exp.port, "/debug/mem")
            payload = json.loads(body)
            assert "http_probe" in payload["by_component"]
            assert payload["by_component"]["http_probe"][
                "device_bytes"] == 12345
        finally:
            exp.stop()
            obs_mem.release(t)

    def test_debug_payload_top_bound(self):
        toks = [obs_mem.account("payload_probe", name=f"p{i}",
                                device_bytes=i + 1) for i in range(5)]
        try:
            payload = obs_mem.debug_payload(top=2)
            assert len(payload["top"]) <= 2
        finally:
            for t in toks:
                obs_mem.release(t)


# ---------------------------------------------------------------------------
# workspace_bytes attribution (the docstring-audit satellite)
# ---------------------------------------------------------------------------

class TestWorkspaceAttribution:
    def test_brute_force_tile_honors_and_records_budget(self, rng):
        """The XLA tiled brute-force path reads Resources.workspace_bytes
        (the docstring's claim, now pinned): a smaller budget yields a
        smaller recorded workspace, and the recorded bytes never exceed
        the budget it was sized under (beyond the 8-row tile floor)."""
        from raft_tpu.neighbors.brute_force import knn

        x = rng.random((300, 12)).astype(np.float32)
        q = rng.random((64, 12)).astype(np.float32)

        def recorded(ws):
            knn(x, q, k=3, metric="l1",  # l1 never routes to the fused path
                res=Resources(workspace_bytes=ws))
            snap = obs.snapshot()["raft_tpu_mem_workspace_bytes"]["series"]
            return [s["value"] for s in snap
                    if s["labels"].get("op") == "brute_force.knn"][0]

        small_budget = 300 * 14 * 4 * 16
        small = recorded(small_budget)
        big = recorded(64 << 20)
        assert small <= small_budget, (
            "recorded workspace exceeds the budget the tile was sized "
            f"under: {small} > {small_budget}")
        assert small < big, (small, big)


# ---------------------------------------------------------------------------
# overhead (pytest.ini obs_overhead marker)
# ---------------------------------------------------------------------------

@pytest.mark.obs_overhead
def test_disabled_ledger_hot_path_is_one_flag_check():
    """obs.disable() must reduce account() to a single module-flag check:
    per-call added cost vs a trivial call under 5 us (same bound and slack
    discipline as the instrument-decorator smoke)."""
    import time

    def raw():
        return None

    obs.disable()
    try:
        n = 20000
        for _ in range(200):
            raw(), obs_mem.account("ov", device_bytes=1)
        t0 = time.perf_counter()
        for _ in range(n):
            raw()
        t_raw = time.perf_counter() - t0
        t0 = time.perf_counter()
        for _ in range(n):
            obs_mem.account("ov", device_bytes=1)
        t_led = time.perf_counter() - t0
    finally:
        obs.enable()
    per_call = (t_led - t_raw) / n
    assert per_call < 5e-6, f"disabled account() {per_call * 1e6:.2f} us/call"
    assert obs_mem.totals()["allocations"] >= 0  # and recorded nothing new
    assert not [r for r in obs_mem.breakdown() if r["component"] == "ov"]


# ---------------------------------------------------------------------------
# hbm stats (CPU backend: documented absence, ledger fallback)
# ---------------------------------------------------------------------------

def test_hbm_stats_cpu_fallback_contract():
    """On the CPU test platform memory_stats() reports nothing usable —
    hbm_stats() must return a dict (possibly empty) and never raise; the
    ledger gauges are the documented fallback."""
    out = obs_mem.hbm_stats()
    assert isinstance(out, dict)
    for stats in out.values():
        assert set(stats) <= {"bytes_in_use", "peak_bytes_in_use",
                              "bytes_limit"}
