"""Aux-subsystem tests: interruptible, output config, temporary buffer,
kmeans runtime-surface parity.

Analogues of pylibraft's test_z_interruptible.py, config-driven output tests,
and the raft_runtime kmeans entry points (raft_runtime/cluster/kmeans.hpp).
"""

import threading

import numpy as np
import pytest

import raft_tpu.config as config
from raft_tpu.cluster import kmeans
from raft_tpu.core import InterruptedException, interruptible, synchronize, temporary_device_buffer
from raft_tpu.core.interruptible import cancel, get_token, yield_no_throw
from raft_tpu.random import make_blobs


def test_interruptible_cancel_same_thread():
    cancel()  # cancel own token
    with pytest.raises(InterruptedException):
        synchronize()
    # flag cleared on throw — next sync passes
    synchronize()


def test_interruptible_cancel_cross_thread():
    state = {}

    def worker():
        tok = get_token()
        state["tid"] = threading.get_ident()
        state["ready"].set()
        state["go"].wait()
        try:
            for _ in range(1000):
                synchronize()
                import time

                time.sleep(0.001)
            state["result"] = "completed"
        except InterruptedException:
            state["result"] = "cancelled"

    state["ready"] = threading.Event()
    state["go"] = threading.Event()
    t = threading.Thread(target=worker)
    t.start()
    state["ready"].wait()
    cancel(state["tid"])  # cancel from the controller thread
    state["go"].set()
    t.join()
    assert state["result"] == "cancelled"


def test_yield_no_throw():
    cancel()
    assert yield_no_throw() is True
    assert yield_no_throw() is False


def test_interruptible_context():
    with interruptible() as tok:
        assert not tok.cancelled()


def test_config_output_as(rng):
    from raft_tpu.config import auto_convert_output

    @auto_convert_output
    def produce():
        import jax.numpy as jnp

        return jnp.ones((3, 3)), jnp.zeros((2,))

    try:
        config.set_output_as("numpy")
        a, b = produce()
        assert isinstance(a, np.ndarray) and isinstance(b, np.ndarray)
        config.set_output_as(lambda arr: ("converted", np.asarray(arr).shape))
        a, _ = produce()
        assert a == ("converted", (3, 3))
        with pytest.raises(ValueError):
            config.set_output_as("cupy")
    finally:
        config.set_output_as("jax")


def test_config_wired_into_public_api(rng):
    from raft_tpu.neighbors import knn

    x = rng.random((50, 4)).astype(np.float32)
    try:
        config.set_output_as("numpy")
        d, i = knn(x, x[:5], 3)
        assert isinstance(d, np.ndarray) and isinstance(i, np.ndarray)
    finally:
        config.set_output_as("jax")
    import jax

    d, _ = knn(x, x[:5], 3)
    assert isinstance(d, jax.Array)


def test_weighted_update_centroids_fractional_weights(rng):
    # regression: divisor must be the true weight total, not max(total, 1)
    x = np.array([[0.0, 0.0], [1.0, 1.0], [10.0, 10.0]], np.float32)
    c0 = np.array([[0.4, 0.4], [10.0, 10.0]], np.float32)
    w = np.full(3, 0.01, np.float32)
    c1, _ = kmeans.update_centroids(x, c0, sample_weights=w)
    np.testing.assert_allclose(np.asarray(c1)[0], [0.5, 0.5], atol=1e-5)
    np.testing.assert_allclose(np.asarray(c1)[1], [10.0, 10.0], atol=1e-5)


def test_temporary_device_buffer():
    host = np.arange(12, dtype=np.float32).reshape(3, 4)
    with temporary_device_buffer(host, writeback=True) as buf:
        buf.array = buf.array * 2
    np.testing.assert_allclose(host, np.arange(12, dtype=np.float32).reshape(3, 4) * 2)


def test_kmeans_init_plus_plus_and_update(rng):
    x, labels_true = make_blobs(n_samples=300, n_features=5, n_clusters=4, seed=0)
    x = np.asarray(x)
    c0 = kmeans.init_plus_plus(x, 4, seed=1)
    assert np.asarray(c0).shape == (4, 5)
    # ++ seeds are spread out: no two identical centers
    c0n = np.asarray(c0)
    assert np.unique(c0n, axis=0).shape[0] == 4

    c1, labels = kmeans.update_centroids(x, c0)
    assert np.asarray(c1).shape == (4, 5)
    # one Lloyd step must not increase cost
    cost0 = float(kmeans.cluster_cost(x, c0))
    cost1 = float(kmeans.cluster_cost(x, c1))
    assert cost1 <= cost0 + 1e-5


def test_kmeans_find_k(rng):
    x, _ = make_blobs(n_samples=400, n_features=4, n_clusters=3, cluster_std=0.3, seed=2)
    best_k, scores = kmeans.find_k(np.asarray(x), range(2, 6))
    assert best_k == 3, scores
