"""Observability subsystem (ISSUE 2): registry semantics, Prometheus export,
compile attribution, comms counters, instrumented entry points, disabled-mode
no-op, and the config._convert list/dict regression.

Tests that read the DEFAULT registry always diff to_json() snapshots —
other tests in the same process legitimately accumulate series there.
"""

import math
import re
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from raft_tpu import obs
from raft_tpu.obs import metrics as obs_metrics


@pytest.fixture(autouse=True)
def _metrics_enabled():
    """Every test starts (and leaves) with metrics enabled — a failing
    disabled-mode test must not silence the rest of the suite."""
    obs.enable()
    yield
    obs.enable()


# ---------------------------------------------------------------------------
# registry semantics
# ---------------------------------------------------------------------------


class TestRegistry:
    def test_counter_labels_and_accumulate(self):
        reg = obs.Registry()
        c = reg.counter("req_total", "requests")
        c.inc(op="a")
        c.inc(2, op="a")
        c.inc(op="b")
        snap = reg.snapshot()["req_total"]
        assert snap["type"] == "counter"
        by = {tuple(s["labels"].items()): s["value"] for s in snap["series"]}
        assert by[(("op", "a"),)] == 3.0
        assert by[(("op", "b"),)] == 1.0

    def test_label_order_is_canonical(self):
        reg = obs.Registry()
        c = reg.counter("c_total")
        c.inc(a="1", b="2")
        c.inc(b="2", a="1")
        assert len(reg.snapshot()["c_total"]["series"]) == 1

    def test_gauge_set(self):
        reg = obs.Registry()
        g = reg.gauge("g")
        g.set(5, shard="0")
        g.set(7, shard="0")
        assert reg.snapshot()["g"]["series"][0]["value"] == 7.0

    def test_kind_conflict_raises(self):
        reg = obs.Registry()
        reg.counter("x_total")
        with pytest.raises(ValueError, match="already registered"):
            reg.histogram("x_total")

    def test_histogram_count_sum_and_quantiles(self):
        reg = obs.Registry()
        h = reg.histogram("lat_seconds", buckets=(0.01, 0.1, 1.0))
        for v in (0.005, 0.05, 0.05, 0.5):
            h.observe(v, op="x")
        s = reg.snapshot()["lat_seconds"]["series"][0]
        assert s["count"] == 4
        assert s["sum"] == pytest.approx(0.605)
        # cumulative buckets: 1 under 0.01, 3 under 0.1, 4 under 1.0
        assert s["buckets"] == {"0.01": 1, "0.1": 3, "1.0": 4, "+Inf": 4}
        # median lands in the (0.01, 0.1] bucket; p99 in (0.1, 1.0]
        assert 0.01 <= h.quantile(0.5, op="x") <= 0.1
        assert 0.1 <= h.quantile(0.99, op="x") <= 1.0
        assert math.isnan(h.quantile(0.5, op="missing"))

    def test_histogram_overflow_bucket(self):
        reg = obs.Registry()
        h = reg.histogram("h", buckets=(1.0,))
        h.observe(2.0)
        s = reg.snapshot()["h"]["series"][0]
        assert s["buckets"] == {"1.0": 0, "+Inf": 1}

    def test_reset_clears_series_keeps_definitions(self):
        reg = obs.Registry()
        reg.counter("a_total", "help").inc()
        reg.reset()
        snap = reg.snapshot()
        assert snap["a_total"]["series"] == []
        assert snap["a_total"]["help"] == "help"

    def test_disabled_mutators_are_noops(self):
        reg = obs.Registry()
        c = reg.counter("c_total")
        h = reg.histogram("h")
        obs.disable()
        try:
            c.inc()
            h.observe(1.0)
        finally:
            obs.enable()
        assert reg.to_json() == {}

    def test_thread_safety_smoke(self):
        import threading

        reg = obs.Registry()
        c = reg.counter("n_total")

        def worker():
            for _ in range(1000):
                c.inc(op="t")

        ts = [threading.Thread(target=worker) for _ in range(8)]
        [t.start() for t in ts]
        [t.join() for t in ts]
        assert reg.to_json() == {'n_total{op="t"}': 8000.0}


# ---------------------------------------------------------------------------
# Prometheus text format
# ---------------------------------------------------------------------------

# one metric line of the text exposition format: name{labels} value; label
# values may contain \" and \\ escapes (the exposition-format grammar)
_LV = r'"(?:[^"\\\n]|\\.)*"'
_SAMPLE_RE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*'                     # metric name
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*=' + _LV +            # first label
    r'(,[a-zA-Z_][a-zA-Z0-9_]*=' + _LV + r')*\})?'   # more labels
    r' -?[0-9.e+-]+(\.[0-9]+)?$'                     # value
)


class TestPrometheus:
    def test_golden_output(self):
        reg = obs.Registry()
        reg.counter("raft_tpu_demo_total", "demo counter").inc(3, op="knn")
        h = reg.histogram("raft_tpu_demo_seconds", "demo latency",
                          buckets=(0.1, 1.0))
        h.observe(0.05, op="knn")
        h.observe(0.5, op="knn")
        assert reg.to_prometheus() == (
            "# HELP raft_tpu_demo_seconds demo latency\n"
            "# TYPE raft_tpu_demo_seconds histogram\n"
            'raft_tpu_demo_seconds_bucket{le="0.1",op="knn"} 1\n'
            'raft_tpu_demo_seconds_bucket{le="1.0",op="knn"} 2\n'
            'raft_tpu_demo_seconds_bucket{le="+Inf",op="knn"} 2\n'
            'raft_tpu_demo_seconds_sum{op="knn"} 0.55\n'
            'raft_tpu_demo_seconds_count{op="knn"} 2\n'
            "# HELP raft_tpu_demo_total demo counter\n"
            "# TYPE raft_tpu_demo_total counter\n"
            'raft_tpu_demo_total{op="knn"} 3\n'
        )

    def test_default_registry_parses_under_grammar(self):
        """Every line of the LIVE registry (whatever other tests added) must
        be a comment or a valid sample line — the scrape contract."""
        obs.counter("raft_tpu_grammar_total").inc(1, weird='va"l\\ue')
        text = obs.to_prometheus()
        assert text.endswith("\n")
        for line in text.splitlines():
            if line.startswith("# HELP ") or line.startswith("# TYPE "):
                continue
            assert _SAMPLE_RE.match(line), f"bad exposition line: {line!r}"

    def test_label_escaping(self):
        reg = obs.Registry()
        reg.counter("e_total").inc(1, path='a"b\\c')
        assert 'path="a\\"b\\\\c"' in reg.to_prometheus()

    def test_non_finite_gauge_exports(self):
        # NaN/±Inf are legal exposition-format sample values; the export
        # must not crash a scrape on them
        reg = obs.Registry()
        reg.gauge("g").set(float("nan"), s="a")
        reg.gauge("g").set(float("inf"), s="b")
        text = reg.to_prometheus()
        assert 'g{s="a"} nan' in text and 'g{s="b"} inf' in text


# ---------------------------------------------------------------------------
# to_json / delta
# ---------------------------------------------------------------------------


def test_to_json_and_delta():
    reg = obs.Registry()
    reg.counter("c_total").inc(2, op="a")
    before = reg.to_json()
    reg.counter("c_total").inc(3, op="a")
    reg.counter("c_total").inc(1, op="b")
    d = obs.delta(before, reg.to_json())
    assert d == {'c_total{op="a"}': 3.0, 'c_total{op="b"}': 1.0}


# ---------------------------------------------------------------------------
# compile attribution
# ---------------------------------------------------------------------------


class TestCompileAttribution:
    def test_cold_jit_vs_persistent_cache_hit(self, tmp_path):
        """A forced cold jit must attribute compile seconds + a cache miss;
        re-compiling the same program after clearing jax's in-memory caches
        must count a persistent-cache hit instead."""
        from raft_tpu.config import enable_compilation_cache

        enable_compilation_cache(str(tmp_path / "jit"))

        def f(x):
            return (x * 3.0 + 1.0).sum() * 7.0

        x = jnp.ones((173, 59))  # unique shape: nothing else compiled it
        with obs.attribution() as cold:
            jax.jit(f)(x).block_until_ready()
        assert cold.available
        assert cold.compile_s > 0 and cold.programs >= 1
        assert cold.cache_misses >= 1
        assert cold.cache_hits == 0

        jax.clear_caches()  # drop the in-memory executable, keep the disk one
        with obs.attribution() as warm:
            jax.jit(f)(x).block_until_ready()
        assert warm.cache_hits >= 1
        assert warm.cache_misses == 0

    def test_warm_call_attributes_nothing(self):
        g = jax.jit(lambda x: x + 2.0)
        x = jnp.ones((8, 8))
        g(x).block_until_ready()
        with obs.attribution() as rec:
            g(x).block_until_ready()
        assert rec.programs == 0 and rec.compile_s == 0.0

    def test_registry_split_is_recorded(self):
        before = obs.to_json()
        jax.jit(lambda x: x * 5.0 - 2.0)(jnp.ones((91, 17))).block_until_ready()
        d = obs.delta(before, obs.to_json())
        assert d.get('raft_tpu_compile_seconds_sum{stage="compile"}', 0) > 0
        assert d.get('raft_tpu_compile_seconds_count{stage="compile"}', 0) >= 1


# ---------------------------------------------------------------------------
# comms counters (8-device CPU mesh)
# ---------------------------------------------------------------------------


class TestCommsCounters:
    def test_allreduce_bytes_and_calls(self, mesh8):
        from jax.sharding import PartitionSpec as P

        from raft_tpu.comms.comms import Comms, shard_along

        comms = Comms(mesh8, "data")
        before = obs.to_json()
        fn = jax.jit(comms.shard_map(
            lambda x: comms.allreduce(x), in_specs=(P("data"),),
            out_specs=P("data")))
        x = shard_along(mesh8, "data", jnp.ones((8, 128), jnp.float32))
        np.asarray(fn(x))
        np.asarray(fn(x))  # cached program: traced once, counted once
        d = obs.delta(before, obs.to_json())
        lbl = '{axis="data",op="allreduce",size="8"}'
        # per-shard payload: (1, 128) f32 = 512 bytes, recorded at trace time
        assert d[f"raft_tpu_collective_bytes_total{lbl}"] == 512
        assert d[f"raft_tpu_collective_calls_total{lbl}"] == 1

    def test_every_collective_records_its_op(self, mesh8):
        from jax.sharding import PartitionSpec as P

        from raft_tpu.comms.comms import Comms, shard_along

        comms = Comms(mesh8, "data")
        before = obs.to_json()

        def step(x):
            y = comms.allgather(x)
            y = comms.reducescatter(y.reshape(8, -1)[:, :x.shape[-1]])
            z = comms.shift(x)
            comms.barrier()
            return x + z + y.reshape(x.shape)

        fn = jax.jit(comms.shard_map(step, in_specs=(P("data"),),
                                     out_specs=P("data")))
        np.asarray(fn(shard_along(mesh8, "data",
                                  jnp.ones((8, 16), jnp.float32))))
        d = obs.delta(before, obs.to_json())
        for op in ("allgather", "reducescatter", "shift", "barrier"):
            key = (f'raft_tpu_collective_calls_total{{axis="data",op="{op}",'
                   f'size="8"}}')
            assert d.get(key, 0) >= 1, (op, d)

    def test_distributed_knn_records_collectives(self, mesh8):
        from raft_tpu.comms.comms import Comms
        from raft_tpu.parallel import knn as pknn

        comms = Comms(mesh8, "data")
        rng = np.random.default_rng(0)
        x = rng.random((256, 16)).astype(np.float32)
        q = rng.random((24, 16)).astype(np.float32)
        before = obs.to_json()
        d_out, i_out = pknn.knn(comms, x, q, 4)
        assert np.asarray(i_out).shape == (24, 4)
        d = obs.delta(before, obs.to_json())
        gathered = sum(v for k, v in d.items()
                       if k.startswith("raft_tpu_collective_bytes_total")
                       and 'op="allgather"' in k)
        # per-shard merge gathers (24, 4) f32 dists + i32 ids = 2 * 384 B
        # (0 when the jitted driver program was already cached in-process —
        # then the call metric below still proves the path was live)
        calls = d.get('raft_tpu_call_seconds_count{k="4",op="parallel.knn",'
                      'size="8"}', 0)
        assert calls == 1, d
        assert gathered in (0, 768), d


# ---------------------------------------------------------------------------
# instrumented entry points (the ISSUE acceptance shape)
# ---------------------------------------------------------------------------


class TestInstrumentedEntryPoints:
    def test_ivf_pq_build_search_snapshot(self):
        """obs.snapshot() after one ivf_pq.build + search shows nonzero
        build/search histograms and a compile-vs-execute split."""
        from raft_tpu.neighbors import ivf_pq

        rng = np.random.default_rng(1)
        x = rng.random((640, 28)).astype(np.float32)  # unique shape: cold jit
        q = rng.random((33, 28)).astype(np.float32)
        before = obs.to_json()
        idx = ivf_pq.build(ivf_pq.IndexParams(n_lists=8, pq_dim=14, seed=0), x)
        ivf_pq.search(ivf_pq.SearchParams(n_probes=4), idx, q, 5)
        d = obs.delta(before, obs.to_json())

        bl = '{dtype="float32",n_lists="8",op="ivf_pq.build"}'
        sl = '{k="5",n_probes="4",op="ivf_pq.search"}'
        assert d[f"raft_tpu_call_seconds_count{bl}"] == 1
        assert d[f"raft_tpu_call_seconds_sum{bl}"] > 0
        assert d[f"raft_tpu_call_seconds_count{sl}"] == 1
        assert d[f"raft_tpu_call_seconds_sum{sl}"] > 0
        # compile-vs-execute split: cold shapes attribute compile seconds,
        # and the split never exceeds the wall
        assert d[f"raft_tpu_call_compile_seconds_sum{bl}"] > 0
        assert (d[f"raft_tpu_call_compile_seconds_sum{bl}"]
                <= d[f"raft_tpu_call_seconds_sum{bl}"])
        assert d[f'raft_tpu_items_total{{op="ivf_pq.build"}}'] == 640
        assert d[f'raft_tpu_items_total{{op="ivf_pq.search"}}'] == 33

    def test_brute_force_and_select_k_record(self):
        from raft_tpu.matrix.select_k import select_k
        from raft_tpu.neighbors.brute_force import knn

        rng = np.random.default_rng(2)
        x = rng.random((300, 8)).astype(np.float32)
        before = obs.to_json()
        knn(x, x[:10], 3)
        select_k(jnp.asarray(rng.random((6, 50), dtype=np.float64)
                             .astype(np.float32)), 4)
        d = obs.delta(before, obs.to_json())
        assert d.get('raft_tpu_items_total{op="brute_force.knn"}', 0) == 10
        assert d.get('raft_tpu_items_total{op="matrix.select_k"}', 0) == 6

    def test_disabled_mode_is_a_noop_on_brute_force(self):
        """With metrics disabled the instrumented brute-force path records
        NOTHING — not even series creation."""
        from raft_tpu.neighbors.brute_force import knn

        rng = np.random.default_rng(3)
        x = rng.random((200, 8)).astype(np.float32)
        knn(x, x[:4], 2)  # warm the jit so the disabled call is pure dispatch
        obs.disable()
        try:
            before = obs.to_json()
            d_out, i_out = knn(x, x[:4], 2)
            assert np.asarray(i_out).shape == (4, 2)  # results unaffected
            assert obs.to_json() == before
        finally:
            obs.enable()


# ---------------------------------------------------------------------------
# obs_overhead tier-1 smoke (pytest.ini marker)
# ---------------------------------------------------------------------------


@pytest.mark.obs_overhead
def test_disabled_instrument_overhead_is_noise():
    """The decorator's disabled path must be one flag check: per-call added
    cost under 5 us (actual ~0.3 us; the bound is 15x slack for CI noise).
    Guards against accidentally hot-path-costly instrumentation."""
    from raft_tpu.obs.instrument import instrument

    def raw(x):
        return x + 1

    wrapped = instrument("overhead_smoke")(raw)
    obs.disable()
    try:
        n = 20000
        # warm both
        for _ in range(200):
            raw(1), wrapped(1)
        t0 = time.perf_counter()
        for _ in range(n):
            raw(1)
        t_raw = time.perf_counter() - t0
        t0 = time.perf_counter()
        for _ in range(n):
            wrapped(1)
        t_wrapped = time.perf_counter() - t0
    finally:
        obs.enable()
    per_call = (t_wrapped - t_raw) / n
    assert per_call < 5e-6, f"disabled-mode overhead {per_call * 1e6:.2f} us/call"


@pytest.mark.obs_overhead
def test_disabled_brute_force_within_noise_of_raw():
    """Instrumented brute-force search with metrics disabled vs the raw
    (undecorated) call: medians within noise. The raw callable is the
    decorator's __wrapped__, i.e. the identical pipeline minus obs."""
    from raft_tpu.neighbors.brute_force import knn

    rng = np.random.default_rng(4)
    x = jnp.asarray(rng.random((500, 16)).astype(np.float32))
    q = jnp.asarray(rng.random((8, 16)).astype(np.float32))
    raw = knn.__wrapped__

    def med(fn):
        ts = []
        for _ in range(15):
            t0 = time.perf_counter()
            jax.block_until_ready(fn(x, q, 3)[0])
            ts.append(time.perf_counter() - t0)
        return sorted(ts)[len(ts) // 2]

    # warm the shared jit cache before timing either side
    jax.block_until_ready(raw(x, q, 3)[0])
    obs.disable()
    try:
        m_raw = med(raw)
        m_inst = med(knn)
    finally:
        obs.enable()
    # generous: dispatch on a CPU mesh is ~100us-1ms and jittery; the
    # disabled decorator adds <1us. 2x + 2ms absorbs scheduler noise.
    assert m_inst <= m_raw * 2 + 2e-3, (m_inst, m_raw)


# ---------------------------------------------------------------------------
# config._convert list/dict regression (satellite)
# ---------------------------------------------------------------------------


def test_convert_recurses_into_lists_and_dicts():
    from raft_tpu import config
    from raft_tpu.config import auto_convert_output

    @auto_convert_output
    def multi():
        a = jnp.arange(3)
        return {"pair": (a, a + 1), "items": [a * 2], "n": 5}

    config.set_output_as("numpy")
    try:
        out = multi()
    finally:
        config.set_output_as("jax")
    assert isinstance(out["pair"][0], np.ndarray)
    assert isinstance(out["pair"][1], np.ndarray)
    assert isinstance(out["items"][0], np.ndarray)
    assert out["n"] == 5
    np.testing.assert_array_equal(out["items"][0], [0, 2, 4])


def test_logger_basic_config_formats_and_replaces():
    import importlib
    import io
    import logging

    # raft_tpu.core re-exports the Logger OBJECT as `logger`, which shadows
    # the module on attribute access — import the module explicitly
    rlog = importlib.import_module("raft_tpu.core.logger")

    buf = io.StringIO()
    lg = rlog.basic_config(level=rlog.INFO, stream=buf)
    lg.info("hello %d", 7)
    text = buf.getvalue()
    assert "hello 7" in text and "[INFO]" in text and "[raft_tpu]" in text
    # second call REPLACES the handler (no double logging)
    buf2 = io.StringIO()
    rlog.basic_config(level=rlog.WARN, stream=buf2)
    lg.warning("again")
    assert buf2.getvalue().count("again") == 1
    assert "again" not in buf.getvalue()
    # restore the library-default quiet logger for the rest of the suite
    lg.removeHandler(rlog._handler)
    rlog._handler = None
    lg.addHandler(logging.NullHandler())
    lg.propagate = True
    lg.setLevel(logging.NOTSET)


def test_build_metrics_coarse_trainer():
    """raft_tpu_build_* metrics (ISSUE 6, docs/observability.md): the
    balanced coarse trainer emits the assignment-pass counter, the
    sampled-rows gauge, and per-phase build walls — the series a capacity
    plan reads to verify mini-batch EM actually killed the full passes."""
    import numpy as np

    from raft_tpu.cluster import kmeans_balanced
    from raft_tpu.cluster.kmeans_balanced import KMeansBalancedParams
    from raft_tpu.obs import metrics

    rng = np.random.default_rng(0)
    x = rng.standard_normal((2048, 8)).astype(np.float32)
    before = obs.to_json()
    kmeans_balanced.fit(
        KMeansBalancedParams(n_iters=6, seed=0, train_mode="minibatch",
                             batch_rows=256), x, 8)
    d = obs.delta(before, obs.to_json())
    em_key = ('raft_tpu_build_assignment_passes_total'
              '{driver="single",mode="minibatch",phase="em"}')
    fin_key = ('raft_tpu_build_assignment_passes_total'
               '{driver="single",mode="minibatch",phase="final"}')
    assert d.get(em_key) == 6.0, d
    assert d.get(fin_key) == 1.0, d
    # gauge: rows per EM iteration == the batch
    snap = metrics.snapshot()["raft_tpu_build_sampled_rows"]["series"]
    mb = [s for s in snap if s["labels"].get("mode") == "minibatch"
          and s["labels"].get("driver") == "single"]
    assert mb and mb[0]["value"] == 256.0, snap
    # per-phase walls observed
    phases = {s["labels"]["phase"]
              for s in metrics.snapshot()[
                  "raft_tpu_build_phase_seconds"]["series"]}
    assert {"kmeans_balanced/em", "kmeans_balanced/final"} <= phases, phases
    # the full em/final/fill decomposition through an IVF build: the same
    # series the distributed driver emits, so dashboards compare 1:1
    from raft_tpu.neighbors import ivf_flat

    before = obs.to_json()
    ivf_flat.build(ivf_flat.IndexParams(n_lists=8, seed=0,
                                        kmeans_train_mode="minibatch",
                                        kmeans_batch_rows=256),
                   rng.standard_normal((1024, 8)).astype(np.float32))
    d2 = obs.delta(before, obs.to_json())
    got = {k.split('phase="')[1].split('"')[0]: v for k, v in d2.items()
           if "assignment_passes" in k}
    assert got == {"em": 20.0, "final": 1.0, "fill": 1.0}, got


# ---------------------------------------------------------------------------
# HTTP exporter (ISSUE 7 satellite): scrapeable without a wrapper
# ---------------------------------------------------------------------------


class TestHttpExporter:
    def test_serves_prometheus_text_and_stops_cleanly(self):
        import urllib.error
        import urllib.request

        obs.counter("raft_tpu_items_total", "rows").inc(1, op="exporter")
        exp = obs.start_http_exporter(0)  # ephemeral loopback port
        try:
            assert exp.port > 0
            # a second start returns the live exporter, not a second port
            assert obs.start_http_exporter(0) is exp
            url = f"http://127.0.0.1:{exp.port}/metrics"
            with urllib.request.urlopen(url, timeout=5) as resp:
                assert resp.status == 200
                assert resp.headers["Content-Type"].startswith(
                    "text/plain; version=0.0.4")
                body = resp.read().decode()
            assert 'raft_tpu_items_total{op="exporter"}' in body
            assert "# TYPE raft_tpu_items_total counter" in body
        finally:
            obs.stop_http_exporter()
        with pytest.raises(urllib.error.URLError):
            urllib.request.urlopen(
                f"http://127.0.0.1:{exp.port}/metrics", timeout=1)
        obs.stop_http_exporter()  # idempotent

    def test_custom_registry_and_context_manager(self):
        import urllib.request

        reg = obs.Registry()
        reg.gauge("raft_tpu_serve_queue_depth", "rows").set(7, stream="s")
        with obs.MetricsExporter(port=0, registry=reg) as exp:
            # the exposition lives at /metrics ONLY (explicit routing —
            # tests/test_obs_quality.py covers the 404 contract)
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{exp.port}/metrics",
                timeout=5).read().decode()
        assert 'raft_tpu_serve_queue_depth{stream="s"} 7' in body
        # the default registry's series must NOT leak into a custom one
        assert "raft_tpu_compile" not in body
