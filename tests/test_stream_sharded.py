"""Sharded serving tier tests (tier-1 ``stream`` marker).

The acceptance spine is the parity suite: a 1-shard ShardedMutableIndex
must be BIT-EQUAL to a plain MutableIndex under the same
upsert/delete/compact script (the sharded composition may not change a
single returned id), multi-shard search must match a fresh build over
exactly the live rows, and a compaction swap on ONE shard under live load
must lose nothing. Deterministic by construction: injected clocks,
compactors driven via ``run_once()``/``compact()``, no wall-clock sleeps
in assertions.
"""

import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu import stream
from raft_tpu.core.errors import RaftError
from raft_tpu.neighbors import brute_force, ivf_flat
from raft_tpu.serve import SearchService

pytestmark = pytest.mark.stream


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


@pytest.fixture
def data(rng):
    return rng.standard_normal((260, 16)).astype(np.float32)


@pytest.fixture
def queries(rng):
    return rng.standard_normal((5, 16)).astype(np.float32)


def bf_build(x):
    return brute_force.BruteForce().build(jnp.asarray(x))


def sharded_bf(data, n_shards, **kw):
    return stream.ShardedMutableIndex(data, n_shards=n_shards,
                                      build=bf_build, **kw)


def bf_gids(live_mat, live_gids, queries, k):
    """Ground truth over an explicit live-row set, mapped to global ids."""
    _, pos = brute_force.knn(jnp.asarray(live_mat), jnp.asarray(queries), k)
    pos = np.asarray(pos)
    return np.where(pos >= 0, np.asarray(live_gids)[np.clip(pos, 0, None)], -1)


# -- routing ------------------------------------------------------------------

def test_shard_of_stable_and_balanced():
    ids = np.arange(100_000)
    s1 = stream.shard_of(ids, 8)
    s2 = stream.shard_of(ids, 8)
    np.testing.assert_array_equal(s1, s2)  # stable across calls/processes
    counts = np.bincount(s1, minlength=8)
    # an avalanche mix over sequential ids stays near-uniform
    assert counts.min() > 0.8 * counts.mean(), counts
    assert counts.max() < 1.2 * counts.mean(), counts
    assert set(np.unique(stream.shard_of(ids[:100], 3))) <= {0, 1, 2}


def test_constructor_validations(data):
    with pytest.raises(RaftError, match="fewer shards"):
        # 4 rows over 16 shards: some shard must come up empty
        sharded_bf(data[:4], 16)
    with pytest.raises(RaftError, match="n_shards"):
        sharded_bf(data, 0)
    with pytest.raises(RaftError, match="devices"):
        sharded_bf(data, 4, devices=jax.devices()[:2])


# -- the parity spine ---------------------------------------------------------

def test_one_shard_parity_bitequal(data, queries, rng):
    """The satellite acceptance bit: the SAME upsert/delete/compact script
    on a 1-shard ShardedMutableIndex and a plain MutableIndex returns
    bit-equal ids (and matching distances) at every step — the sharded
    composition (scan halves + padded one-dispatch merge) may not change
    a single result."""
    clock = FakeClock()
    plain = stream.MutableIndex(bf_build(data), delta_capacity=64,
                                clock=clock)
    shard = sharded_bf(data, 1, delta_capacity=64, clock=clock)

    def check():
        dp, ip = plain.search(queries, 10)
        ds, is_ = shard.search(queries, 10)
        np.testing.assert_array_equal(np.asarray(ip), np.asarray(is_))
        np.testing.assert_allclose(np.asarray(dp), np.asarray(ds),
                                   rtol=1e-6)

    check()
    ins = rng.standard_normal((12, 16)).astype(np.float32)
    g1 = plain.upsert(ins)
    g2 = shard.upsert(ins)
    np.testing.assert_array_equal(g1, g2)  # fresh-id assignment matches
    check()
    for m in (plain, shard):
        m.delete([3, 17, int(g1[4]), 9999])
    check()
    for m in (plain, shard):
        rep = m.compact(mode="rebuild")
        # the two dead SEALED slots reclaim; the dead delta row just
        # doesn't fold (11 of 12 inserted rows were still alive)
        assert rep["reclaimed"] == 2 and rep["folded"] == 11
    check()
    g3, g4 = plain.upsert(ins[:2] + 1.0), shard.upsert(ins[:2] + 1.0)
    np.testing.assert_array_equal(g3, g4)
    check()
    assert plain.size == shard.size


def test_multi_shard_search_matches_fresh_build(data, queries, rng):
    """4 hash-routed shards (uneven sizes by construction), upserts and
    deletes: scatter-gather results equal a fresh brute-force build over
    exactly the live rows — identical global ids, matching distances."""
    shard = sharded_bf(data, 4, delta_capacity=64)
    sizes = [sh._state.id_map.shape[0] for sh in shard.shards]
    assert sum(sizes) == len(data) and len(set(sizes)) > 1, sizes
    ins = rng.standard_normal((20, 16)).astype(np.float32)
    gids = shard.upsert(ins)
    dele = [3, 17, 44, 101, int(gids[4])]
    assert shard.delete(dele) == 5
    live_mask = np.ones(len(data), bool)
    live_mask[[3, 17, 44, 101]] = False
    ins_mask = np.ones(20, bool)
    ins_mask[4] = False
    live_mat = np.concatenate([data[live_mask], ins[ins_mask]])
    live_g = np.concatenate([np.nonzero(live_mask)[0],
                             np.asarray(gids)[ins_mask]])
    want = bf_gids(live_mat, live_g, queries, 10)
    d, got = shard.search(queries, 10)
    np.testing.assert_array_equal(np.asarray(got), want)
    dref, _ = brute_force.knn(jnp.asarray(live_mat), jnp.asarray(queries), 10)
    np.testing.assert_allclose(np.asarray(d), np.asarray(dref), rtol=1e-5)
    assert shard.size == len(live_g)


def test_uneven_tiny_corpus_underfill_sentinels(rng):
    """A corpus smaller than k x shards still reports the shared
    underfill contract: live rows first, then id -1 at +inf."""
    data = rng.standard_normal((24, 8)).astype(np.float32)
    q = rng.standard_normal((3, 8)).astype(np.float32)
    shard = sharded_bf(data, 3, delta_capacity=8)
    shard.delete(np.arange(20))  # 4 live rows remain
    d, i = shard.search(q, 10)
    d, i = np.asarray(d), np.asarray(i)
    assert (i[:, 4:] == -1).all() and np.isinf(d[:, 4:]).all()
    assert (i[:, :4] >= 0).all() and np.isfinite(d[:, :4]).all()


def test_exact_search_matches_brute_force(data, queries, rng):
    shard = sharded_bf(data, 4, delta_capacity=32)
    gids = shard.upsert(rng.standard_normal((8, 16)).astype(np.float32))
    shard.delete([0, 1, int(gids[0])])
    # build the live set from the shards' own bookkeeping
    mats, gs = [], []
    for sh in shard.shards:
        st = sh._state
        alive = np.nonzero(st.sealed_alive)[0]
        mats.append(st.store[alive])
        gs.append(st.id_map[alive])
        dal = np.nonzero(st.delta_alive[:st.delta_n])[0]
        mats.append(st.delta[dal])
        gs.append(st.delta_ids[dal])
    live_mat = np.concatenate([m for m in mats if len(m)])
    live_g = np.concatenate([g for g in gs if len(g)])
    want = bf_gids(live_mat, live_g, queries, 10)
    _, got = shard.exact_search(queries, 10)
    np.testing.assert_array_equal(np.asarray(got), want)


# -- writes -------------------------------------------------------------------

def test_upsert_routes_by_hash_and_read_your_writes(data, queries):
    shard = sharded_bf(data, 4, delta_capacity=32)
    g = shard.upsert(queries[0:1] + 1e-3)
    home = int(stream.shard_of(g, 4)[0])
    assert shard.shards[home].stats()["delta_rows"] == 1
    assert all(sh.stats()["delta_rows"] == 0
               for s, sh in enumerate(shard.shards) if s != home)
    _, ids = shard.search(queries, 5)
    assert int(np.asarray(ids)[0, 0]) == int(g[0])
    # upsert under the same id replaces the old copy on its home shard
    far = (queries[0:1] * 0.0) + 100.0
    shard.upsert(far, ids=[int(g[0])])
    _, ids2 = shard.search(queries, 5)
    assert int(g[0]) != int(np.asarray(ids2)[0, 0])
    assert shard.size == len(data) + 1  # one live copy per id


def test_upsert_atomic_across_shards(data):
    """Whole-or-nothing admission: a batch that would overflow ONE home
    shard is refused before ANY row lands on any shard."""
    shard = sharded_bf(data, 2, delta_capacity=8)
    # find ids homing to shard 0 / shard 1
    cand = np.arange(10_000, 30_000)
    homes = stream.shard_of(cand, 2)
    to0, to1 = cand[homes == 0], cand[homes == 1]
    shard.upsert(np.zeros((7, 16), np.float32) + 0.5, ids=to0[:7])
    before = shard.stats()["delta_rows"]
    mixed = np.concatenate([to0[7:9], to1[:3]])  # overflows shard 0
    with pytest.raises(stream.DeltaFullError, match="shard 0"):
        shard.upsert(np.ones((5, 16), np.float32), ids=mixed)
    assert shard.stats()["delta_rows"] == before  # nothing landed anywhere
    shard.upsert(np.ones((3, 16), np.float32), ids=to1[:3])  # still admits


# -- staggered compaction -----------------------------------------------------

def test_staggered_compaction_folds_one_shard_at_a_time(data, queries, rng):
    clock = FakeClock()
    shard = sharded_bf(data, 4, delta_capacity=16, clock=clock)
    comp = stream.Compactor(
        shard, policy=stream.CompactionPolicy(delta_fill=0.5,
                                              tombstone_ratio=None),
        clock=clock)
    assert comp.due() is None
    ins = rng.standard_normal((40, 16)).astype(np.float32)
    gids = shard.upsert(ins)
    folded_shards = []
    while comp.due():
        rep = comp.run_once()
        assert rep["trigger"] == "delta_fill"
        # ONE shard folds per cycle; its siblings' epochs are untouched
        folded_shards.append(rep["shard"])
        assert rep["shard_epoch"] == 1
    assert len(folded_shards) >= 2  # the watermark staggers across shards
    assert len(set(folded_shards)) == len(folded_shards)  # distinct shards
    assert shard.stats()["epoch"] == len(folded_shards)
    # results unchanged by the folds
    live_g = np.concatenate([np.arange(len(data)), gids])
    live_mat = np.concatenate([data, ins])
    want = bf_gids(live_mat, live_g, queries, 10)
    _, got = shard.search(queries, 10)
    np.testing.assert_array_equal(np.asarray(got), want)


def test_age_trigger_folds_the_stalest_shard_not_the_fullest(data):
    """An age trip must chase the shard holding the OLDEST delta: picking
    the fullest would fold busy shards forever while the quiet shard's
    stale write never seals (and a `while due(): run_once()` loop would
    livelock — due() stays tripped on the min age across shards)."""
    clock = FakeClock()
    shard = sharded_bf(data, 4, delta_capacity=16, clock=clock)
    comp = stream.Compactor(
        shard, policy=stream.CompactionPolicy(delta_fill=None,
                                              tombstone_ratio=None,
                                              max_age_s=5.0), clock=clock)
    cand = np.arange(10_000, 40_000)
    homes = stream.shard_of(cand, 4)
    quiet, busy = cand[homes == 1], cand[homes == 3]
    shard.upsert(np.zeros((1, 16), np.float32), ids=quiet[:1])  # t=0
    clock.advance(3.0)
    shard.upsert(np.ones((5, 16), np.float32), ids=busy[:5])  # fuller, young
    clock.advance(2.5)  # quiet shard is 5.5s stale, busy only 2.5s
    assert comp.due() == "age"
    rep = comp.run_once()
    assert rep["shard"] == 1 and rep["folded"] == 1, rep
    assert comp.due() is None  # the standing trip cleared — no livelock
    clock.advance(3.0)  # now the busy shard's write crosses the horizon
    assert comp.due() == "age"
    assert comp.run_once()["shard"] == 3


def test_tombstone_watermark_picks_dirtiest_shard(data):
    clock = FakeClock()
    shard = sharded_bf(data, 4, delta_capacity=16, clock=clock)
    # tombstone >25% of ONE shard's sealed rows
    victim = 2
    vic_ids = shard.shards[victim]._state.id_map
    shard.delete(vic_ids[:len(vic_ids) // 3 + 1])
    comp = stream.Compactor(
        shard, policy=stream.CompactionPolicy(delta_fill=None,
                                              tombstone_ratio=0.25),
        clock=clock)
    assert comp.due() == "tombstone_ratio"
    rep = comp.run_once()
    assert rep["shard"] == victim and rep["mode"] == "rebuild"
    assert rep["reclaimed"] == len(vic_ids) // 3 + 1
    assert comp.due() is None  # the other shards were never dirty


def test_swap_under_load_on_one_shard_loses_nothing(data, queries):
    """The acceptance-critical property scaled to the mesh: a compaction
    swap of ONE shard landing mid-load (reads + writes in flight on ALL
    shards) fails zero requests and loses zero writes."""
    shard = sharded_bf(data, 4, delta_capacity=64, name="load")
    svc = SearchService(max_batch=8, max_wait_us=200.0, max_queue_rows=512)
    svc.publish("load", shard, k=5)
    shard.warm(svc.buckets, ks=(5,))
    comp = stream.Compactor(
        shard, publisher=svc, name="load", ks=(5,),
        policy=stream.CompactionPolicy(delta_fill=0.125,
                                       tombstone_ratio=None))
    errors, done = [], []
    lock = threading.Lock()

    def reader(tid):
        for j in range(25):
            try:
                _, ids = svc.search("load", data[(tid * 31 + j) % 200:
                                                 (tid * 31 + j) % 200 + 1], 5)
                with lock:
                    done.append(int(np.asarray(ids)[0, 0]))
            except Exception as e:  # any loss is a failure
                with lock:
                    errors.append(repr(e))

    threads = [threading.Thread(target=reader, args=(t,)) for t in range(4)]
    for t in threads:
        t.start()
    swaps = 0
    for step in range(30):
        svc.upsert("load", data[step % 100:step % 100 + 2] + 0.5)
        while comp.due():
            comp.run_once()
            swaps += 1
    for t in threads:
        t.join(60)
        assert not t.is_alive(), "reader wedged"
    svc.shutdown()
    assert errors == []
    assert len(done) == 100
    assert swaps >= 2 and shard.stats()["epoch"] == swaps
    # staggered: folds landed on more than one shard across the run
    assert len({sh.stats()["epoch"] for sh in shard.shards}) >= 1
    assert sum(sh.stats()["epoch"] for sh in shard.shards) == swaps


# -- device pinning + warm discipline ----------------------------------------

def test_device_pinning_places_shards_apart(data):
    devs = jax.devices()[:4]
    shard = sharded_bf(data, 4, devices=devs, delta_capacity=16)
    placed = [next(iter(sh._state.delta_view[0].devices()))
              for sh in shard.shards]
    assert placed == devs, placed
    # sealed side follows the pin too
    sealed = [next(iter(sh._state.sealed.dataset.devices()))
              for sh in shard.shards]
    assert sealed == devs, sealed
    # results come back mergeable regardless of the pins
    d, i = shard.search(data[:3], 5)
    assert np.asarray(i).shape == (3, 5)


def test_warm_ladder_keeps_sharded_hot_path_compile_free(data, queries):
    """The zero-cold-compile discipline across the mesh: after warm() +
    publish, searches at every per-shard delta fill level, the writes
    between them, and a STAGGERED mid-window shard fold + republish
    trigger zero compiles — asserted via obs compile attribution.
    Device-pinned, so placement is part of what the warm must cover."""
    from raft_tpu.obs import compile as obs_compile

    if not obs_compile.install():  # pragma: no cover - ancient jax
        pytest.skip("jax.monitoring unavailable")
    clock = FakeClock()
    devs = jax.devices()[:2]

    def run(name):
        shard = sharded_bf(data, 2, devices=devs, delta_capacity=16,
                           clock=clock, name=name)
        svc = SearchService(max_batch=4, clock=clock, start_workers=False)
        svc.publish(name, shard, k=5)
        shard.warm(svc.buckets, ks=(5,))
        comp = stream.Compactor(
            shard, publisher=svc, name=name, ks=(5,),
            policy=stream.CompactionPolicy(delta_fill=0.5,
                                           tombstone_ratio=None),
            clock=clock)
        for step in range(24):
            shard.upsert(data[step:step + 1] + 0.5, ids=[1000 + step])
            while comp.due():
                comp.run_once()
            fut = svc.submit(name, queries[:2], 5)
            clock.advance(1.0)
            svc.pump()
            fut.result(timeout=0)
        svc.shutdown()

    run("rehearsal")  # compiles the epoch program set
    with obs_compile.attribution() as rec:
        run("live")  # the same schedule must replay warm
    assert rec.compile_s == 0.0 and rec.programs == 0


# -- serve + obs integration --------------------------------------------------

def test_serve_publish_resolves_sharded_duck_typed(data, queries):
    clock = FakeClock()
    shard = sharded_bf(data, 3, delta_capacity=16, clock=clock)
    svc = SearchService(max_batch=4, clock=clock, start_workers=False)
    rep = svc.publish("mesh", shard, k=5)
    assert rep["version"] == 1
    g = svc.upsert("mesh", queries[0:1] + 1e-3)  # write path opened
    fut = svc.submit("mesh", queries[:1], 5)
    clock.advance(1.0)
    svc.pump()
    assert int(np.asarray(fut.result(timeout=0)[1])[0, 0]) == int(g[0])
    assert svc.delete("mesh", g) == 1
    # a compactor-style hook republish keeps the write path open
    svc.publish("mesh", shard.searcher(), k=5)
    svc.upsert("mesh", queries[1:2])
    with pytest.raises(RaftError, match="wrap time"):
        svc.publish("mesh2", shard, search_params=object(), warm=False)
    svc.shutdown()


def test_canary_oracle_covers_the_mesh(data, queries):
    """obs.quality.exact_oracle resolves a ShardedMutableIndex unchanged;
    for an exact sealed kind the canary's estimate over served results is
    exactly 1.0 (the served pipeline IS the oracle here)."""
    from raft_tpu.obs import quality
    from raft_tpu.serve import bucket_sizes

    clock = FakeClock()
    shard = sharded_bf(data, 3, delta_capacity=16, clock=clock)
    canary = quality.RecallCanary(
        quality.exact_oracle(shard), k=5, sample_rate=1.0,
        buckets=bucket_sizes(4), name="mesh")
    svc = SearchService(max_batch=4, clock=clock, start_workers=False,
                        canary=canary)
    svc.publish("mesh", shard, k=5)
    for lo in range(0, 12, 4):
        fut = svc.submit("mesh", data[lo:lo + 4], 5)
        clock.advance(1.0)
        svc.pump()
        fut.result(timeout=0)
    canary.drain()
    est = canary.estimate()
    assert est["reranked"] > 0
    assert est["recall"] == 1.0, est
    svc.shutdown()


def test_requestlog_per_shard_spans(data, queries):
    """A traced sharded search carves into per-shard spans
    (stream/shard<i>/{sealed,delta}) plus the one cross-shard merge —
    the straggler-shard attribution /debug/requests exists for."""
    from raft_tpu.obs import requestlog

    shard = sharded_bf(data, 2, delta_capacity=16)
    with requestlog.collect() as c:
        shard.search(queries, 5)
    for s in range(2):
        assert f"stream/shard{s}/stream/sealed" in c.spans, c.spans
        assert f"stream/shard{s}/stream/delta" in c.spans, c.spans
        assert c.notes[f"stream/shard{s}/stream_epoch"] == 0
    assert "stream/merge" in c.spans
    assert c.notes["stream_shards"] == 2


def test_sharded_stats_and_gauges(data):
    from raft_tpu.obs import metrics

    shard = sharded_bf(data, 4, delta_capacity=16, name="gauges")
    shard.upsert(data[:3] + 0.5)
    st = shard.stats()
    assert st["shards"] == 4 and len(st["per_shard"]) == 4
    assert st["delta_rows"] == 3
    assert st["live"] == len(data) + 3
    # binding-shard semantics: aggregate fill is the max, not the mean
    assert st["delta_fill"] == max(p["delta_fill"] for p in st["per_shard"])
    snap = metrics.to_json()
    assert snap.get('raft_tpu_stream_shards{name="gauges"}') == 4
    # per-shard series report under name/shard<i>; aggregate under the name
    assert 'raft_tpu_stream_delta_rows{name="gauges"}' in snap
    assert any(k.startswith('raft_tpu_stream_delta_rows{name="gauges/shard')
               for k in snap), [k for k in snap if "gauges" in k]


def test_drift_store_interleaves_shards(data):
    shard = sharded_bf(data, 4, delta_capacity=16)
    store = shard._drift_store()
    assert store is not None and store.shape[1] == 16
    assert store.shape[0] == len(data)  # small corpus: everything rides
    none_store = sharded_bf(data, 2, delta_capacity=16,
                            retain_vectors=False)
    assert none_store._drift_store() is None


# -- byte dtypes --------------------------------------------------------------

def test_byte_sharded_index(rng):
    xb = rng.integers(-128, 128, (180, 16), dtype=np.int8)
    shard = stream.ShardedMutableIndex(
        xb, n_shards=2, delta_capacity=16,
        build=lambda x: ivf_flat.build(
            ivf_flat.IndexParams(n_lists=4, list_dtype="int8", seed=0), x),
        search_params=ivf_flat.SearchParams(n_probes=16))
    assert shard.query_dtype == "int8"
    with pytest.raises(RaftError, match="int8"):
        shard.upsert(np.zeros((1, 16), np.float32))
    q = xb[:3]
    g = shard.upsert(q[0:1])  # exact duplicate of query 0
    _, ids = shard.search(q, 3)
    assert int(g[0]) in set(np.asarray(ids)[0].tolist())
