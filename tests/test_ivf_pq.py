"""IVF-PQ tests — recall-threshold acceptance vs brute force
(reference analogue: cpp/test/neighbors/ann_ivf_pq.cuh, pylibraft
test_ivf_pq.py)."""

import numpy as np
import pytest
from scipy.spatial import distance as sp_dist

from raft_tpu.neighbors import ivf_pq, refine
from raft_tpu.random import make_blobs


def _recall(got_ids, true_ids):
    hits = 0
    for g, t in zip(got_ids, true_ids):
        hits += len(set(g.tolist()) & set(t.tolist()))
    return hits / true_ids.size


@pytest.fixture(scope="module")
def data():
    x, _ = make_blobs(6000, 32, n_clusters=60, cluster_std=2.0, seed=0)
    q, _ = make_blobs(80, 32, n_clusters=60, cluster_std=2.0, seed=1)
    return np.asarray(x), np.asarray(q)


class TestBuild:
    def test_index_structure(self, data):
        x, _ = data
        idx = ivf_pq.build(ivf_pq.IndexParams(n_lists=32, pq_dim=8, seed=0), x)
        # hot lists may split into capacity-bounded sub-lists sharing a center
        # (_list_utils.split_oversized), so n_lists is a lower bound
        assert idx.n_lists >= 32
        assert idx.capacity <= max(2 * 6000 // 32 + 8, 16)
        assert idx.pq_dim == 8
        assert idx.pq_len == 4  # 32 / 8
        assert idx.size == 6000
        assert idx.codebooks.shape == (8, 16, 4)  # 2**pq_bits=16 (TPU default 4)

    def test_pq_bits(self, data):
        x, _ = data
        idx = ivf_pq.build(ivf_pq.IndexParams(n_lists=16, pq_dim=8, pq_bits=4, seed=0), x)
        assert idx.codebooks.shape[1] == 16
        assert np.asarray(idx.list_codes).max() < 16

    def test_default_pq_dim(self, data):
        x, _ = data
        idx = ivf_pq.build(ivf_pq.IndexParams(n_lists=16, seed=0), x)
        # bits-aware heuristic: equal code bytes to the reference default
        # (d/2 dims at 8 bits == d dims at 4 bits == d/2 bytes)
        assert idx.pq_dim == 32  # d at the pq_bits=4 default

    def test_rotation_is_orthonormal(self, data):
        x, _ = data
        idx = ivf_pq.build(
            ivf_pq.IndexParams(n_lists=16, pq_dim=8, force_random_rotation=True, seed=0), x
        )
        r = np.asarray(idx.rotation)
        np.testing.assert_allclose(r @ r.T, np.eye(r.shape[0]), atol=1e-4)

    def test_bad_pq_bits(self, data):
        from raft_tpu.core import RaftError

        with pytest.raises(RaftError):
            ivf_pq.build(ivf_pq.IndexParams(pq_bits=16), data[0])


class TestSearch:
    def test_recall_all_probes(self, data):
        x, q = data
        idx = ivf_pq.build(ivf_pq.IndexParams(n_lists=32, pq_dim=32, seed=0), x)
        _, i = ivf_pq.search(ivf_pq.SearchParams(n_probes=32), idx, q, k=10)
        true_i = np.argsort(sp_dist.cdist(q, x, "sqeuclidean"), 1)[:, :10]
        rec = _recall(np.asarray(i), true_i)
        assert rec > 0.8, rec  # PQ-lossy exact-probe recall

    def test_recall_grows_with_probes(self, data):
        x, q = data
        idx = ivf_pq.build(ivf_pq.IndexParams(n_lists=64, pq_dim=32, seed=0), x)
        true_i = np.argsort(sp_dist.cdist(q, x, "sqeuclidean"), 1)[:, :10]
        recalls = [
            _recall(np.asarray(ivf_pq.search(ivf_pq.SearchParams(n_probes=p), idx, q, 10)[1]), true_i)
            for p in (2, 8, 32, 64)
        ]
        assert recalls[-1] >= recalls[0]
        assert recalls[-1] > 0.75, recalls

    def test_refine_recovers_exact_ranking(self, data):
        """The reference pipeline: ivf_pq search k0 > k → exact refine → k
        (pylibraft ivf_pq+refine pattern, CAGRA build dependency)."""
        x, q = data
        idx = ivf_pq.build(ivf_pq.IndexParams(n_lists=32, pq_dim=32, seed=0), x)
        _, cand = ivf_pq.search(ivf_pq.SearchParams(n_probes=32), idx, q, k=40)
        d, i = refine(x, q, np.asarray(cand), k=10)
        true_i = np.argsort(sp_dist.cdist(q, x, "sqeuclidean"), 1)[:, :10]
        rec = _recall(np.asarray(i), true_i)
        assert rec > 0.9, rec

    def test_per_cluster_codebooks(self, data):
        x, q = data
        idx = ivf_pq.build(
            ivf_pq.IndexParams(n_lists=16, pq_dim=16, codebook_kind="per_cluster", seed=0), x
        )
        # one codebook per list (sub-lists share their parent's codebook)
        assert idx.codebooks.shape[0] == idx.n_lists >= 16
        _, i = ivf_pq.search(ivf_pq.SearchParams(n_probes=idx.n_lists), idx, q, k=10)
        true_i = np.argsort(sp_dist.cdist(q, x, "sqeuclidean"), 1)[:, :10]
        rec = _recall(np.asarray(i), true_i)
        # pq_dim=8 on d=32 is 4x compression; ~0.55 matches per_subspace at the
        # same ratio (codebook kinds are quality-equivalent here)
        assert rec > 0.45, rec

    def test_inner_product(self, data):
        x, q = data
        idx = ivf_pq.build(
            ivf_pq.IndexParams(n_lists=32, pq_dim=32, metric="inner_product", seed=0), x
        )
        _, i = ivf_pq.search(ivf_pq.SearchParams(n_probes=32), idx, q, k=10)
        true_i = np.argsort(-(q @ x.T), 1)[:, :10]
        rec = _recall(np.asarray(i), true_i)
        assert rec > 0.7, rec

    def test_bf16_lut(self, data):
        x, q = data
        idx = ivf_pq.build(ivf_pq.IndexParams(n_lists=32, pq_dim=16, seed=0), x)
        _, i32 = ivf_pq.search(ivf_pq.SearchParams(n_probes=32), idx, q, k=10)
        _, i16 = ivf_pq.search(
            ivf_pq.SearchParams(n_probes=32, lut_dtype="bfloat16"), idx, q, k=10
        )
        # bf16 LUT must stay close to f32 ranking
        overlap = np.mean([
            len(set(a.tolist()) & set(b.tolist())) / 10
            for a, b in zip(np.asarray(i32), np.asarray(i16))
        ])
        assert overlap > 0.85, overlap


class TestExtend:
    def test_extend(self, data):
        x, q = data
        idx = ivf_pq.build(ivf_pq.IndexParams(n_lists=16, pq_dim=8, seed=0), x[:5000])
        idx = ivf_pq.extend(idx, x[5000:], np.arange(5000, 6000, dtype=np.int32))
        assert idx.size == 6000
        ids = np.asarray(idx.list_ids)
        assert sorted(ids[ids >= 0].tolist()) == list(range(6000))

    def test_build_empty_then_extend(self, data):
        x, _ = data
        idx = ivf_pq.build(
            ivf_pq.IndexParams(n_lists=16, pq_dim=8, add_data_on_build=False, seed=0), x
        )
        assert idx.size == 0
        idx = ivf_pq.extend(idx, x)
        assert idx.size == 6000


class TestSerialize:
    def test_roundtrip(self, tmp_path, data):
        x, q = data
        idx = ivf_pq.build(ivf_pq.IndexParams(n_lists=16, pq_dim=8, seed=0), x)
        p = str(tmp_path / "pq.bin")
        ivf_pq.save(idx, p)
        idx2 = ivf_pq.load(p)
        d1, i1 = ivf_pq.search(ivf_pq.SearchParams(n_probes=8), idx, q, k=5)
        d2, i2 = ivf_pq.search(ivf_pq.SearchParams(n_probes=8), idx2, q, k=5)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
        np.testing.assert_allclose(np.asarray(d1), np.asarray(d2), rtol=1e-6)


class TestPq8Split:
    """pq_bits=8 nibble-split (two-stage 4+4-bit residual VQ per subspace):
    the scan separates the 256-entry LUT into two 16-entry stage LUTs plus a
    precomputed per-vector cross term (list_consts). No reference analogue —
    the reference's smem-gather LUT (detail/ivf_pq_compute_similarity-inl.cuh)
    is bits-insensitive; on TPU the one-hot contraction axis shrinks 8x."""

    def test_structure(self, data):
        x, _ = data
        idx = ivf_pq.build(ivf_pq.IndexParams(n_lists=16, pq_dim=8, pq_bits=8, seed=0), x)
        assert idx.pq_split
        assert idx.codebooks.shape == (8, 32, 4)  # 2 stages x 16 entries
        assert idx.list_consts.shape == (idx.n_lists, idx.capacity)
        # codes use the full byte (hi/lo nibbles)
        assert np.asarray(idx.list_codes).max() > 15

    def test_scores_are_exact_composed_distances(self, data):
        """Reported distance == ||q - center - R^T(cb1[hi]+cb2[lo])||^2 —
        verifies the separated LUTs + cross-term constant reassemble the
        joint score exactly (up to f32 accumulation)."""
        x, q = data
        idx = ivf_pq.build(ivf_pq.IndexParams(n_lists=16, pq_dim=8, pq_bits=8, seed=0), x)
        d, i = ivf_pq.search(ivf_pq.SearchParams(n_probes=idx.n_lists), idx, q[:8], k=5)
        d, i = np.asarray(d), np.asarray(i)
        cb = np.asarray(idx.codebooks)
        codes = np.asarray(idx.list_codes)
        lids = np.asarray(idx.list_ids)
        rot = np.asarray(idx.rotation)
        cen = np.asarray(idx.centers)
        for r in range(8):
            for c in range(5):
                l, p = np.argwhere(lids == i[r, c])[0]
                cd = codes[l, p]
                dec = np.concatenate(
                    [cb[s, cd[s] >> 4] + cb[s, 16 + (cd[s] & 15)]
                     for s in range(idx.pq_dim)])
                recon = cen[l] + rot.T @ dec
                # f32 accumulation of large cancelling terms (||r||^2 bias +
                # stage LUTs + cross consts) skews ~0.1% relative vs the
                # numpy double-precision recompute
                np.testing.assert_allclose(
                    d[r, c], ((q[r] - recon) ** 2).sum(), rtol=5e-3, atol=1e-2)

    def test_recall_beats_pq4_same_pq_dim(self, data):
        """8 bits via 4+4 residual stages should rank at least as well as the
        single-stage 4-bit codebook at the SAME pq_dim (so pq8 spends twice
        the code bytes) — the added stage must buy quality."""
        x, q = data
        true_i = np.argsort(sp_dist.cdist(q, x, "sqeuclidean"), 1)[:, :10]
        r8 = _recall(np.asarray(ivf_pq.search(
            ivf_pq.SearchParams(n_probes=32),
            ivf_pq.build(ivf_pq.IndexParams(n_lists=32, pq_dim=16, pq_bits=8, seed=0), x),
            q, 10)[1]), true_i)
        r4 = _recall(np.asarray(ivf_pq.search(
            ivf_pq.SearchParams(n_probes=32),
            ivf_pq.build(ivf_pq.IndexParams(n_lists=32, pq_dim=16, pq_bits=4, seed=0), x),
            q, 10)[1]), true_i)
        assert r8 >= r4 - 0.02, (r8, r4)

    def test_joint_flag_off(self, data):
        x, q = data
        idx = ivf_pq.build(ivf_pq.IndexParams(
            n_lists=16, pq_dim=8, pq_bits=8, pq8_split=False, seed=0), x)
        assert not idx.pq_split
        assert idx.codebooks.shape == (8, 256, 4)
        assert idx.list_consts.shape == (idx.n_lists, 0)
        _, i = ivf_pq.search(ivf_pq.SearchParams(n_probes=16), idx, q, k=10)
        true_i = np.argsort(sp_dist.cdist(q, x, "sqeuclidean"), 1)[:, :10]
        # pq_dim=8 on d=32 is 4x compression with a 6k-row trainset for 256
        # codes/subspace; ~0.53 matches the per-cluster fixture at this ratio
        assert _recall(np.asarray(i), true_i) > 0.45

    def test_inner_product_defaults_to_joint(self, data):
        # metric-aware auto: the Minkowski coarseness costs IP ranking far
        # more than L2 (review-measured recall@5 0.375 joint vs 0.075 split
        # on tight clusters), so pq8_split=None resolves to joint for IP
        x, _ = data
        idx = ivf_pq.build(ivf_pq.IndexParams(
            n_lists=16, pq_dim=16, pq_bits=8, metric="inner_product", seed=0), x)
        assert not idx.pq_split
        assert idx.codebooks.shape[1] == 256

    def test_inner_product_split_forced(self, data):
        x, q = data
        idx = ivf_pq.build(ivf_pq.IndexParams(
            n_lists=16, pq_dim=16, pq_bits=8, pq8_split=True,
            metric="inner_product", seed=0), x)
        assert idx.pq_split
        # IP scoring is exactly separable: no consts stored
        assert idx.list_consts.shape == (idx.n_lists, 0)
        _, i = ivf_pq.search(ivf_pq.SearchParams(n_probes=16), idx, q, k=10)
        true_i = np.argsort(-(q @ x.T), 1)[:, :10]
        assert _recall(np.asarray(i), true_i) > 0.6

    def test_extend_carries_consts(self, data):
        x, _ = data
        idx = ivf_pq.build(ivf_pq.IndexParams(n_lists=16, pq_dim=8, pq_bits=8, seed=0),
                           x[:5000])
        idx2 = ivf_pq.extend(idx, x[5000:], np.arange(5000, 6000, dtype=np.int32))
        assert idx2.size == 6000
        # every stored vector has its const where its id lives
        lids = np.asarray(idx2.list_ids)
        consts = np.asarray(idx2.list_consts)
        assert consts.shape == lids.shape
        # re-extending the same rows reproduces identical consts for old rows
        l, p = np.argwhere(lids == 0)[0]
        lids1 = np.asarray(idx.list_ids)
        l1, p1 = np.argwhere(lids1 == 0)[0]
        np.testing.assert_allclose(consts[l, p], np.asarray(idx.list_consts)[l1, p1],
                                   rtol=1e-6)

    def test_per_cluster_split(self, data):
        """per_cluster codebooks x nibble-split: stage training on the pooled
        per-cluster subvectors and the per-cluster cross-consts gather
        (_pq_cross_consts labels branch) compose with the split scan."""
        x, q = data
        idx = ivf_pq.build(ivf_pq.IndexParams(
            n_lists=16, pq_dim=8, pq_bits=8, codebook_kind="per_cluster",
            seed=0), x)
        assert idx.pq_split
        assert idx.codebooks.shape == (idx.n_lists, 32, 4)
        assert idx.list_consts.shape == (idx.n_lists, idx.capacity)
        _, i = ivf_pq.search(ivf_pq.SearchParams(n_probes=idx.n_lists), idx, q, k=10)
        true_i = np.argsort(sp_dist.cdist(q, x, "sqeuclidean"), 1)[:, :10]
        assert _recall(np.asarray(i), true_i) > 0.4

    def test_roundtrip_split(self, tmp_path, data):
        x, q = data
        idx = ivf_pq.build(ivf_pq.IndexParams(n_lists=16, pq_dim=8, pq_bits=8, seed=0), x)
        p = str(tmp_path / "pq8.bin")
        ivf_pq.save(idx, p)
        idx2 = ivf_pq.load(p)
        assert idx2.pq_split
        d1, i1 = ivf_pq.search(ivf_pq.SearchParams(n_probes=8), idx, q, k=5)
        d2, i2 = ivf_pq.search(ivf_pq.SearchParams(n_probes=8), idx2, q, k=5)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
        np.testing.assert_allclose(np.asarray(d1), np.asarray(d2), rtol=1e-6)


class TestCodebookAuto:
    """codebook_kind='auto' trial-trains per-cluster codebooks on the largest
    clusters and adopts them only when they quantize markedly better
    (reference leaves PER_CLUSTER opt-in, ivf_pq_build.cuh:424; the auto mode
    + advisory log are TPU-side additions)."""

    def _lid_data(self, n=6000, d=32, ncl=24, idim=3, seed=5):
        """Cluster-structured residuals: each cluster's points deviate from
        its center inside a private low-dim subspace — per-cluster codebooks'
        best case."""
        rng = np.random.default_rng(seed)
        centers = rng.uniform(0, 10, (ncl, d)).astype(np.float32)
        bases = rng.normal(size=(ncl, idim, d)).astype(np.float32)
        bases /= np.linalg.norm(bases, axis=-1, keepdims=True)
        lab = rng.integers(0, ncl, n)
        z = rng.normal(size=(n, idim)).astype(np.float32)
        return (centers[lab] + np.einsum("ni,nid->nd", z, bases[lab])).astype(np.float32)

    def test_auto_picks_per_cluster_on_structured_residuals(self):
        x = self._lid_data()
        idx = ivf_pq.build(
            ivf_pq.IndexParams(n_lists=24, pq_dim=8, codebook_kind="auto", seed=0), x)
        assert idx.codebook_kind == "per_cluster"
        assert idx.codebooks.shape[0] == idx.n_lists

    def test_auto_keeps_per_subspace_on_shared_residuals(self):
        # iid gaussian data has no per-cluster residual structure (measured
        # trial ratio ~0.98 vs ~0.83 on blob data, threshold 0.9) — note
        # even make_blobs data legitimately profits from per-cluster books
        # when n_lists < n_blobs (each list pools several blobs), so the
        # negative control must be structureless
        rng = np.random.default_rng(11)
        x = rng.standard_normal((6000, 32)).astype(np.float32)
        idx = ivf_pq.build(
            ivf_pq.IndexParams(n_lists=32, pq_dim=8, codebook_kind="auto", seed=0), x)
        assert idx.codebook_kind == "per_subspace"

    def test_default_build_runs_no_trial(self, caplog):
        # the trial is opt-in via codebook_kind="auto": plain per_subspace
        # builds (including internal ones like CAGRA's knn-graph IVF-PQ,
        # which expose no codebook knob) must not pay for it or log advice
        import logging

        x = self._lid_data()
        with caplog.at_level(logging.INFO, logger="raft_tpu"):
            idx = ivf_pq.build(
                ivf_pq.IndexParams(n_lists=24, pq_dim=8, seed=0), x)
        assert idx.codebook_kind == "per_subspace"
        assert not any("codebook" in r.message for r in caplog.records)

    def test_auto_logs_its_decision(self, caplog):
        import logging

        x = self._lid_data()
        with caplog.at_level(logging.INFO, logger="raft_tpu"):
            ivf_pq.build(ivf_pq.IndexParams(
                n_lists=24, pq_dim=8, codebook_kind="auto", seed=0), x)
        assert any("auto codebooks" in r.message for r in caplog.records)


@pytest.mark.slow
def test_int8_lut(rng):
    """int8 LUT (the reference's fp8 smem-LUT analogue, detail/fp_8bit.cuh):
    per-(query,probe) symmetric quantization must track the f32 LUT ranking
    closely at full probe coverage."""
    import jax.numpy as jnp
    from scipy.spatial import distance as sp_dist

    x = rng.random((3000, 32)).astype(np.float32)
    q = rng.random((20, 32)).astype(np.float32)
    idx = ivf_pq.build(ivf_pq.IndexParams(n_lists=16, pq_dim=16, seed=0), jnp.asarray(x))
    d32, i32 = ivf_pq.search(ivf_pq.SearchParams(n_probes=16), idx, q, 10)
    d8, i8 = ivf_pq.search(
        ivf_pq.SearchParams(n_probes=16, lut_dtype="int8"), idx, q, 10)
    i32, i8 = np.asarray(i32), np.asarray(i8)
    overlap = np.mean([len(set(i32[r]) & set(i8[r])) / 10 for r in range(20)])
    assert overlap > 0.8, overlap
    # both should be decent vs exact ground truth
    gt = np.argsort(sp_dist.cdist(q, x, "sqeuclidean"), axis=1)[:, :10]
    rec8 = np.mean([len(set(i8[r]) & set(gt[r])) / 10 for r in range(20)])
    rec32 = np.mean([len(set(i32[r]) & set(gt[r])) / 10 for r in range(20)])
    assert rec8 > rec32 - 0.1, (rec8, rec32)


class TestScanImpls:
    """The scan formulations (SearchParams.scan_impl) must agree: the one-hot
    MXU contraction, the XLA compare+select chain, and the Pallas
    dynamic-gather kernel (interpret mode on the CPU test platform) are three
    spellings of the same Σ_s LUT[s, code_s] (BASELINE.md r04 scan study)."""

    @pytest.mark.parametrize("bits", [4, 8])
    def test_impls_agree(self, data, bits, monkeypatch):
        monkeypatch.setenv("RAFT_TPU_PQ_SCAN_INTERPRET", "1")
        x, q = data
        idx = ivf_pq.build(
            ivf_pq.IndexParams(n_lists=32, pq_dim=8, pq_bits=bits, seed=0), x)
        outs = {}
        for impl in ("onehot", "select", "pallas"):
            d, i = ivf_pq.search(
                ivf_pq.SearchParams(n_probes=8, scan_impl=impl), idx, q, 10)
            outs[impl] = (np.asarray(d), np.asarray(i))
        d0, i0 = outs["onehot"]
        for impl in ("select", "pallas"):
            d, i = outs[impl]
            # tie-robust: the formulations sum scores in different orders, so
            # near-tied candidates may swap ranks — compare id SETS per row
            # and the sorted distances, not positional ids
            for r in range(i.shape[0]):
                assert set(i[r].tolist()) == set(i0[r].tolist()), (impl, r)
            np.testing.assert_allclose(np.sort(d, 1), np.sort(d0, 1),
                                       rtol=1e-5, atol=1e-4, err_msg=impl)

    @pytest.mark.parametrize("S", [24, 96, 192])
    def test_odd_lane_widths_padded(self, S, monkeypatch):
        """pq_dim values that neither divide nor are a multiple of 128 (e.g.
        96, 24 — reachable via pq_bits=4 builds) must route through the
        zero-LUT lane padding, not hand Mosaic a non-128-aligned lane dim
        (r04 advisor finding). Direct kernel parity vs the numpy sum."""
        import jax.numpy as jnp

        from raft_tpu.ops.pq_scan import pq_lut_scan

        rng = np.random.default_rng(0)
        B, cap = 3, 40
        codes = rng.integers(0, 16, (B, cap, S), dtype=np.int8)
        lut = rng.normal(size=(B, 16, S)).astype(np.float32)
        got = np.asarray(pq_lut_scan(
            jnp.asarray(codes), jnp.asarray(lut), interpret=True))
        want = np.take_along_axis(
            lut[:, :, None, :].transpose(0, 2, 1, 3),
            codes[:, :, None, :].astype(np.int64), axis=2
        )[:, :, 0, :].sum(-1)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-4)

    def test_narrow_stage_guard(self, data):
        from raft_tpu.core import RaftError

        x, q = data
        idx = ivf_pq.build(ivf_pq.IndexParams(
            n_lists=16, pq_dim=8, pq_bits=8, pq8_split=False, seed=0), x)
        with pytest.raises(RaftError, match="16-wide"):
            ivf_pq.search(ivf_pq.SearchParams(n_probes=8, scan_impl="select"),
                          idx, q, 10)

    def test_int8_lut_needs_onehot(self, data):
        from raft_tpu.core import RaftError

        x, q = data
        idx = ivf_pq.build(ivf_pq.IndexParams(n_lists=32, pq_dim=8, seed=0), x)
        with pytest.raises(RaftError, match="one-hot"):
            ivf_pq.search(ivf_pq.SearchParams(
                n_probes=8, lut_dtype="int8", scan_impl="select"), idx, q, 10)

    def test_split_consts_validated(self, data):
        import dataclasses

        from raft_tpu.core import RaftError
        import jax.numpy as jnp

        x, q = data
        idx = ivf_pq.build(ivf_pq.IndexParams(
            n_lists=16, pq_dim=8, pq_bits=8, seed=0), x)
        assert idx.pq_split
        broken = dataclasses.replace(
            idx, list_consts=jnp.zeros((idx.n_lists, 0), jnp.float32))
        with pytest.raises(RaftError, match="list_consts"):
            ivf_pq.search(ivf_pq.SearchParams(n_probes=8), broken, q, 10)
        with pytest.raises(RaftError, match="list_consts"):
            ivf_pq.extend(broken, x[:8])


class TestGroupedScan:
    """scan_order='grouped' (probe-major, shared one-hot per list group) must
    agree with the tiled order across metrics, bit widths, LUT dtypes and
    filters — same candidates scored by the same quantizer, only the batching
    differs (BASELINE.md "Round-4 grouped scan")."""

    @pytest.mark.parametrize("bits", [4, 8])
    def test_matches_tiled(self, data, bits):
        x, q = data
        idx = ivf_pq.build(
            ivf_pq.IndexParams(n_lists=32, pq_dim=8, pq_bits=bits, seed=0), x)
        for lut in ("float32", "int8"):
            d1, i1 = ivf_pq.search(ivf_pq.SearchParams(
                n_probes=8, lut_dtype=lut, scan_order="tiled"), idx, q, 10)
            d2, i2 = ivf_pq.search(ivf_pq.SearchParams(
                n_probes=8, lut_dtype=lut, scan_order="grouped"), idx, q, 10)
            i1, i2 = np.asarray(i1), np.asarray(i2)
            overlap = np.mean([len(set(a) & set(b)) / 10
                               for a, b in zip(i1.tolist(), i2.tolist())])
            assert overlap > 0.98, (bits, lut, overlap)  # near-ties may swap
            np.testing.assert_allclose(
                np.sort(np.asarray(d1), 1), np.sort(np.asarray(d2), 1),
                rtol=1e-3, atol=1e-2)

    def test_inner_product_and_filter(self, data):
        x, q = data
        idxip = ivf_pq.build(ivf_pq.IndexParams(
            n_lists=32, pq_dim=8, metric="inner_product", seed=0), x)
        _, i1 = ivf_pq.search(ivf_pq.SearchParams(
            n_probes=8, scan_order="tiled"), idxip, q, 10)
        _, i2 = ivf_pq.search(ivf_pq.SearchParams(
            n_probes=8, scan_order="grouped"), idxip, q, 10)
        overlap = np.mean([len(set(a) & set(b)) / 10 for a, b in
                           zip(np.asarray(i1).tolist(), np.asarray(i2).tolist())])
        assert overlap > 0.98, overlap

        keep = np.ones(len(x), bool)
        keep[::3] = False
        idx = ivf_pq.build(ivf_pq.IndexParams(n_lists=32, pq_dim=8, seed=0), x)
        _, ig = ivf_pq.search(ivf_pq.SearchParams(
            n_probes=8, scan_order="grouped"), idx, q, 10, sample_filter=keep)
        ig = np.asarray(ig)
        banned = set(np.nonzero(~keep)[0].tolist())
        assert not (set(ig[ig >= 0].ravel().tolist()) & banned)

    def test_k_capacity_guard(self, data):
        from raft_tpu.core import RaftError

        x, q = data
        idx = ivf_pq.build(ivf_pq.IndexParams(n_lists=32, pq_dim=8, seed=0), x)
        with pytest.raises(RaftError, match="capacity"):
            ivf_pq.search(ivf_pq.SearchParams(
                n_probes=32, scan_order="grouped"), idx, q, idx.capacity + 1)


class TestByteDatasets:
    """int8/uint8 dataset ingestion end-to-end (reference: the dedicated
    ivf_pq int8_t/uint8_t instantiations, cpp/src/neighbors/ivf_pq_build_*
    — BigANN-class byte data is PQ's home regime). All PQ math runs on the
    exact f32 image of the bytes (uint8 shifted by -128, L2-invariant), so
    recall bars match the float tests'."""

    @pytest.fixture(scope="class")
    def idata(self):
        rng = np.random.default_rng(5)
        # clustered bytes: blob centers + noise, clipped to [0, 255]
        centers = rng.integers(40, 215, (24, 32))
        lab = rng.integers(0, 24, 4000)
        x = np.clip(centers[lab] + rng.normal(0, 12, (4000, 32)), 0, 255)
        qlab = rng.integers(0, 24, 60)
        q = np.clip(centers[qlab] + rng.normal(0, 12, (60, 32)), 0, 255)
        return x.astype(np.uint8), q.astype(np.uint8)

    @pytest.mark.parametrize("dt", [np.uint8, np.int8])
    def test_build_search_recall(self, idata, dt):
        xu, qu = idata
        x = xu if dt == np.uint8 else (xu.astype(np.int16) - 128).astype(np.int8)
        q = qu if dt == np.uint8 else (qu.astype(np.int16) - 128).astype(np.int8)
        idx = ivf_pq.build(ivf_pq.IndexParams(n_lists=32, pq_dim=32, seed=0), x)
        assert idx.data_kind == dt.__name__
        _, i = ivf_pq.search(ivf_pq.SearchParams(n_probes=32), idx, q, k=10)
        d2 = ((q[:, None, :].astype(np.float64)
               - x[None].astype(np.float64)) ** 2).sum(-1)
        true_i = np.argsort(d2, 1)[:, :10]
        rec = _recall(np.asarray(i), true_i)
        assert rec > 0.8, rec  # PQ-lossy exact-probe recall, float parity

    def test_signed_and_shifted_agree(self, idata):
        """uint8 ingestion = the pre-shifted int8 build, identical ids."""
        xu, qu = idata
        xs = (xu.astype(np.int16) - 128).astype(np.int8)
        qs = (qu.astype(np.int16) - 128).astype(np.int8)
        ip = ivf_pq.IndexParams(n_lists=16, pq_dim=8, seed=0)
        _, i_u = ivf_pq.search(ivf_pq.SearchParams(n_probes=16),
                               ivf_pq.build(ip, xu), qu, 10)
        _, i_s = ivf_pq.search(ivf_pq.SearchParams(n_probes=16),
                               ivf_pq.build(ip, xs), qs, 10)
        np.testing.assert_array_equal(np.asarray(i_u), np.asarray(i_s))

    def test_refine_pipeline(self, idata):
        """Byte PQ search k0 > k feeding an exact byte refine — the
        reference's standard BigANN operating point."""
        xu, qu = idata
        idx = ivf_pq.build(ivf_pq.IndexParams(n_lists=32, pq_dim=32, seed=0), xu)
        _, cand = ivf_pq.search(ivf_pq.SearchParams(n_probes=32), idx, qu, k=40)
        _, i = refine(xu, qu, np.asarray(cand), k=10)
        d2 = ((qu[:, None, :].astype(np.float64)
               - xu[None].astype(np.float64)) ** 2).sum(-1)
        true_i = np.argsort(d2, 1)[:, :10]
        rec = _recall(np.asarray(i), true_i)
        assert rec > 0.9, rec

    def test_float_queries_on_uint8_index(self, idata):
        xu, qu = idata
        idx = ivf_pq.build(ivf_pq.IndexParams(n_lists=16, pq_dim=8, seed=0), xu)
        _, i_b = ivf_pq.search(ivf_pq.SearchParams(n_probes=16), idx, qu, 10)
        _, i_f = ivf_pq.search(ivf_pq.SearchParams(n_probes=16), idx,
                               qu.astype(np.float32), 10)
        np.testing.assert_array_equal(np.asarray(i_b), np.asarray(i_f))

    def test_extend_dtype_guard(self, idata):
        from raft_tpu.core import RaftError

        xu, _ = idata
        idx = ivf_pq.build(ivf_pq.IndexParams(n_lists=16, pq_dim=8, seed=0),
                           xu[:3000])
        # a plain astype would wrap the domain mod 256 — must be rejected
        with pytest.raises(RaftError, match="stores uint8"):
            ivf_pq.extend(idx, (xu[3000:].astype(np.int16) - 128).astype(np.int8))
        idx2 = ivf_pq.extend(idx, xu[3000:])
        assert int(np.asarray(idx2.list_sizes).sum()) == len(xu)
        assert idx2.data_kind == "uint8"

    def test_uint8_inner_product_rejected(self, idata):
        from raft_tpu.core import RaftError

        xu, _ = idata
        with pytest.raises(RaftError, match="inner_product"):
            ivf_pq.build(ivf_pq.IndexParams(
                n_lists=16, pq_dim=8, metric="inner_product", seed=0), xu)

    def test_roundtrip_preserves_kind(self, tmp_path, idata):
        xu, qu = idata
        idx = ivf_pq.build(ivf_pq.IndexParams(n_lists=16, pq_dim=8, seed=0), xu)
        p = str(tmp_path / "pq_u8.bin")
        ivf_pq.save(idx, p)
        idx2 = ivf_pq.load(p)
        assert idx2.data_kind == "uint8"
        d1, i1 = ivf_pq.search(ivf_pq.SearchParams(n_probes=8), idx, qu, 5)
        d2, i2 = ivf_pq.search(ivf_pq.SearchParams(n_probes=8), idx2, qu, 5)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
        np.testing.assert_allclose(np.asarray(d1), np.asarray(d2), rtol=1e-6)


class TestResidualScaleNorm:
    """Per-list residual scale normalization (IndexParams.residual_scale
    _norm — the heavytail remedy, VERDICT r5 #2): codes encode r/s_list,
    search folds s_list back in (s^2 for L2, s for IP), so scores stay the
    exact ||r - s*decode||^2 and recall on scale-skewed data recovers."""

    @staticmethod
    def _skewed(rng, n=6000, ncl=32, d=16, q=200):
        """Lognormal per-cluster residual scales — the heavytail family's
        defining symmetry break, at test scale."""
        centers = rng.random((ncl, d)).astype(np.float32) * 10
        scales = rng.lognormal(np.log(0.25), 0.8, ncl).astype(np.float32)
        lab = rng.integers(0, ncl, n)
        x = (centers[lab] + rng.normal(0, 1, (n, d)).astype(np.float32)
             * scales[lab][:, None])
        qs = x[:q] + rng.normal(0, 0.01, (q, d)).astype(np.float32)
        true_i = np.argsort(sp_dist.cdist(qs, x, "sqeuclidean"), 1)[:, :10]
        return x, qs, true_i

    def test_recall_recovers_on_scale_skewed_data(self, rng):
        x, q, true_i = self._skewed(rng)
        recs = {}
        for norm in (False, True):
            idx = ivf_pq.build(ivf_pq.IndexParams(
                n_lists=32, pq_bits=4, pq_dim=8, residual_scale_norm=norm,
                seed=0), x)
            _, ids = ivf_pq.search(ivf_pq.SearchParams(n_probes=8), idx,
                                   q, 10)
            recs[norm] = _recall(np.asarray(ids), true_i)
        # the in-session 100k heavytail A/B measured bare +0.18 absolute;
        # at test scale the gap is smaller but must not invert
        assert recs[True] >= recs[False] - 0.01, recs
        assert recs[True] > 0.5, recs

    def test_scores_are_exact_scaled_decode(self, rng):
        """Returned distances must equal the manual ||r - s*decode||^2
        reconstruction — the folding (r/s into the LUT dots, s^2 back out,
        raw-r bias) is exact algebra, not an approximation."""
        x, q, _ = self._skewed(rng, n=2000, ncl=16)
        idx = ivf_pq.build(ivf_pq.IndexParams(
            n_lists=16, pq_bits=4, pq_dim=8, residual_scale_norm=True,
            seed=0), x)
        assert idx.scale_normed and idx.list_scales.shape[0] == idx.n_lists
        d_got, i_got = ivf_pq.search(
            ivf_pq.SearchParams(n_probes=idx.n_lists), idx, q[:8], 5)
        d_got, i_got = np.asarray(d_got), np.asarray(i_got)
        # locate each hit's (list, slot) to read its code back
        ids_h = np.asarray(idx.list_ids)
        codes_h = np.asarray(idx.list_codes)
        cb = np.asarray(idx.codebooks)           # (pq_dim, 16, pq_len)
        centers_rot = np.asarray(idx.centers_rot)
        scales = np.asarray(idx.list_scales)
        qrot = q[:8] @ np.asarray(idx.rotation).T
        for r in range(8):
            for c in range(5):
                hit = i_got[r, c]
                l, s = np.argwhere(ids_h == hit)[0]
                code = codes_h[l, s]             # (pq_dim,)
                decode = np.stack([cb[j, code[j]] for j in range(len(code))])
                resid = (qrot[r] - centers_rot[l]).reshape(decode.shape)
                want = float(((resid - scales[l] * decode) ** 2).sum())
                np.testing.assert_allclose(d_got[r, c], want, rtol=2e-3,
                                           atol=2e-3)

    def test_grouped_order_matches_tiled(self, rng):
        x, q, _ = self._skewed(rng, n=3000, ncl=16)
        idx = ivf_pq.build(ivf_pq.IndexParams(
            n_lists=16, pq_bits=4, pq_dim=8, residual_scale_norm=True,
            seed=0), x)
        sp_t = ivf_pq.SearchParams(n_probes=4, scan_order="tiled")
        sp_g = ivf_pq.SearchParams(n_probes=4, scan_order="grouped")
        d_t, i_t = ivf_pq.search(sp_t, idx, q[:64], 5)
        d_g, i_g = ivf_pq.search(sp_g, idx, q[:64], 5)
        np.testing.assert_array_equal(np.asarray(i_t), np.asarray(i_g))
        np.testing.assert_allclose(np.asarray(d_t), np.asarray(d_g),
                                   rtol=1e-5, atol=1e-5)

    def test_pq8_split_consts_carry_scale(self, rng):
        """pq_split stores the 2*cb1·cb2 cross term per vector; with scale
        norm it must arrive s^2-folded — search on an all-lists probe would
        misrank otherwise."""
        x, q, true_i = self._skewed(rng, n=3000, ncl=16)
        idx = ivf_pq.build(ivf_pq.IndexParams(
            n_lists=16, pq_bits=8, pq_dim=8, residual_scale_norm=True,
            seed=0), x)
        assert idx.pq_split and idx.scale_normed
        _, ids = ivf_pq.search(ivf_pq.SearchParams(n_probes=8), idx, q, 10)
        assert _recall(np.asarray(ids), true_i) > 0.5

    def test_extend_save_load_roundtrip(self, rng, tmp_path):
        x, q, _ = self._skewed(rng, n=4000, ncl=16)
        idx = ivf_pq.build(ivf_pq.IndexParams(
            n_lists=16, pq_bits=4, pq_dim=8, residual_scale_norm=True,
            seed=0), x[:3000])
        idx = ivf_pq.extend(idx, x[3000:])
        assert idx.list_scales.shape[0] == idx.n_lists
        p = str(tmp_path / "pq_scaled.bin")
        ivf_pq.save(idx, p)
        idx2 = ivf_pq.load(p)
        np.testing.assert_allclose(np.asarray(idx2.list_scales),
                                   np.asarray(idx.list_scales))
        d1, i1 = ivf_pq.search(ivf_pq.SearchParams(n_probes=8), idx, q, 5)
        d2, i2 = ivf_pq.search(ivf_pq.SearchParams(n_probes=8), idx2, q, 5)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))

    def test_load_pre_v7_defaults_disabled(self, rng, tmp_path):
        """A file without residual_scale_norm loads with the (0,) disabled
        sentinel — older indexes never normalized, so that is exact."""
        x, _, _ = self._skewed(rng, n=2000, ncl=16)
        idx = ivf_pq.build(ivf_pq.IndexParams(
            n_lists=16, pq_bits=4, pq_dim=8, seed=0), x)
        p = str(tmp_path / "pq_plain.bin")
        ivf_pq.save(idx, p)
        idx2 = ivf_pq.load(p)
        assert not idx2.scale_normed
        assert idx2.list_scales.shape == (0,)

    def test_per_cluster_composes(self, rng):
        x, q, true_i = self._skewed(rng, n=4000, ncl=16)
        idx = ivf_pq.build(ivf_pq.IndexParams(
            n_lists=16, pq_bits=4, pq_dim=8, codebook_kind="per_cluster",
            residual_scale_norm=True, seed=0), x)
        assert idx.codebook_kind == "per_cluster" and idx.scale_normed
        _, ids = ivf_pq.search(ivf_pq.SearchParams(n_probes=8), idx, q, 10)
        assert _recall(np.asarray(ids), true_i) > 0.5

    def test_inner_product_scale_fold(self, rng):
        """IP folds s (not s^2): returned scores must equal the manual
        q_rot · (c_rot + s*decode) reconstruction exactly (recall is the
        wrong probe here — pq4's IP ranking is coarse regardless of the
        fold, see IndexParams.pq8_split notes)."""
        x, q, _ = self._skewed(rng, n=3000, ncl=16)
        idx = ivf_pq.build(ivf_pq.IndexParams(
            n_lists=16, pq_bits=4, pq_dim=8, metric="inner_product",
            residual_scale_norm=True, seed=0), x)
        d_got, i_got = ivf_pq.search(
            ivf_pq.SearchParams(n_probes=idx.n_lists), idx, q[:8], 5)
        d_got, i_got = np.asarray(d_got), np.asarray(i_got)
        ids_h = np.asarray(idx.list_ids)
        codes_h = np.asarray(idx.list_codes)
        cb = np.asarray(idx.codebooks)
        crot = np.asarray(idx.centers_rot)
        sc = np.asarray(idx.list_scales)
        qrot = q[:8] @ np.asarray(idx.rotation).T
        for r in range(8):
            for c in range(5):
                l, s = np.argwhere(ids_h == i_got[r, c])[0]
                code = codes_h[l, s]
                dec = np.stack([cb[j, code[j]]
                                for j in range(len(code))]).reshape(-1)
                want = float(qrot[r] @ (crot[l] + sc[l] * dec))
                np.testing.assert_allclose(d_got[r, c], want, rtol=2e-3,
                                           atol=2e-3)


class TestFilterUnderfill:
    """Shared filtered-underfill contract (ISSUE 5 satellite): when fewer
    than k rows survive the filter, ids are -1 at +inf (L2) / -inf (IP) —
    same checker as brute_force/ivf_flat/cagra."""

    def test_underfill_sentinels(self, data, check_filter_underfill):
        x, q = data
        idx = ivf_pq.build(ivf_pq.IndexParams(n_lists=16, pq_dim=16, seed=0), x)
        alive = [44, 1023, 5020]
        keep = np.zeros(x.shape[0], bool)
        keep[alive] = True
        d, i = ivf_pq.search(ivf_pq.SearchParams(n_probes=64), idx, q, 10,
                             sample_filter=keep)
        check_filter_underfill(d, i, alive, select_min=True)

    def test_underfill_sentinels_inner_product(self, data,
                                               check_filter_underfill):
        x, q = data
        idx = ivf_pq.build(
            ivf_pq.IndexParams(n_lists=16, pq_dim=16,
                               metric="inner_product", seed=0), x)
        alive = [3, 997]
        keep = np.zeros(x.shape[0], bool)
        keep[alive] = True
        d, i = ivf_pq.search(ivf_pq.SearchParams(n_probes=64), idx, q, 10,
                             sample_filter=keep)
        check_filter_underfill(d, i, alive, select_min=False)


class TestMinibatchEm:
    """Mini-batch coarse EM (ISSUE 6): the 100k IVF-PQ recall anchor must
    hold within tolerance vs full EM at the BENCH operating point shape
    (pq4). Heavy 1M cases live in the slow manifest."""

    def test_minibatch_recall_parity_100k(self):
        import dataclasses

        from raft_tpu.neighbors import brute_force

        n, d, k = 100_000, 32, 10
        x, _ = make_blobs(n, d, n_clusters=500, cluster_std=1.0, seed=9)
        x = np.asarray(x)
        q = x[:300]
        _, gt = brute_force.knn(x, q, k)
        gt = np.asarray(gt)
        base = ivf_pq.IndexParams(n_lists=256, pq_bits=4, pq_dim=16, seed=0,
                                  kmeans_batch_rows=8192)
        sp = ivf_pq.SearchParams(n_probes=8, lut_dtype="bfloat16")
        recs = {}
        for mode in ("full", "minibatch"):
            idx = ivf_pq.build(
                dataclasses.replace(base, kmeans_train_mode=mode), x)
            _, ids = ivf_pq.search(sp, idx, q, k)
            recs[mode] = _recall(np.asarray(ids), gt)
            del idx
        # absolute recall here is set by the shrunk pq4x16 quantizer on
        # d=32 (same convention as the churn smoke: the anchor VALUE is the
        # driver-scale row's job); the bar that matters is PARITY
        assert recs["minibatch"] > 0.3, recs
        assert recs["minibatch"] >= recs["full"] - 0.03, recs


class TestQuantFunnel:
    """Quantization funnel (ISSUE 16): OPQ learned rotation, score-aware
    (anisotropic) codebooks, and the bit-packed fast-scan pre-filter tier
    (binary widen → exact-PQ rerank → caller refine). The load-bearing
    contracts: funnel_widen=1 is BIT-EQUAL to a no-tier twin at the same
    seed (the tier changes WHERE candidates come from, never what width-1
    answers), filtered candidates keep their sentinels through every
    stage, and the raft_tpu/13 codec record round-trips with /12
    read-compat both directions."""

    @pytest.fixture(scope="class")
    def twins(self, data):
        """Classic / 1bit-funnel twin builds at the same seed — identical
        codebooks by construction (signature encoding consumes no RNG)."""
        x, _ = data
        base = dict(n_lists=16, pq_dim=16, seed=0)
        classic = ivf_pq.build(ivf_pq.IndexParams(**base), x)
        funnel = ivf_pq.build(
            ivf_pq.IndexParams(fast_scan="1bit", **base), x)
        return classic, funnel

    def test_structure(self, twins):
        classic, funnel = twins
        assert funnel.has_fast_scan and funnel.fast_scan == "1bit"
        # d_rot=32 → ceil(32/8)=4 packed sign-bit bytes per slot
        assert funnel.list_sig.shape == (funnel.n_lists, funnel.capacity, 4)
        assert funnel.list_sig.dtype == np.uint8
        assert funnel.sig_scales.shape == (funnel.n_lists,)
        assert not classic.has_fast_scan and classic.fast_scan == "none"
        assert classic.list_sig.shape == (classic.n_lists, 0, 0)

    def test_structure_4bit(self, data):
        x, _ = data
        idx = ivf_pq.build(
            ivf_pq.IndexParams(n_lists=16, pq_dim=16, fast_scan="4bit",
                               seed=0), x)
        # d_rot=32 → ceil(32/2)=16 packed nibble bytes per slot
        assert idx.list_sig.shape == (idx.n_lists, idx.capacity, 16)
        assert idx.fast_scan == "4bit"

    def test_width1_bit_equal_classic(self, twins, data):
        """The acceptance anchor: funnel_widen=1 routes the classic scan
        untouched — ids AND distances bit-equal to the no-tier twin."""
        _, q = data
        classic, funnel = twins
        dc, ic = ivf_pq.search(ivf_pq.SearchParams(n_probes=8), classic,
                               q, k=10)
        df, if_ = ivf_pq.search(
            ivf_pq.SearchParams(n_probes=8, funnel_widen=1), funnel, q, k=10)
        np.testing.assert_array_equal(np.asarray(ic), np.asarray(if_))
        np.testing.assert_array_equal(np.asarray(dc), np.asarray(df))

    def test_funnel_recall_1bit(self, twins, data):
        """Widened 1bit funnel holds the classic scan's recall: the binary
        tier only has to RANK the true top-k into the top W·k per chunk."""
        x, q = data
        classic, funnel = twins
        true_i = np.argsort(sp_dist.cdist(q, x, "sqeuclidean"), 1)[:, :10]
        _, ic = ivf_pq.search(ivf_pq.SearchParams(n_probes=32), classic,
                              q, k=10)
        _, if_ = ivf_pq.search(
            ivf_pq.SearchParams(n_probes=32, funnel_widen=16), funnel,
            q, k=10)
        rec_c = _recall(np.asarray(ic), true_i)
        rec_f = _recall(np.asarray(if_), true_i)
        # the anchor is RELATIVE: this coarse pq4x16 codec tops out ~0.43
        # on d=32 blobs, and the widened funnel must track it
        assert rec_f > 0.3, rec_f
        assert rec_f >= rec_c - 0.05, (rec_f, rec_c)

    def test_funnel_recall_4bit_narrower_widen(self, data):
        """4bit's lower estimator variance holds the anchor at half the
        width the 1bit sizing rule starts from (the docs' W=4 start)."""
        x, q = data
        idx = ivf_pq.build(
            ivf_pq.IndexParams(n_lists=16, pq_dim=16, fast_scan="4bit",
                               seed=0), x)
        classic = ivf_pq.build(
            ivf_pq.IndexParams(n_lists=16, pq_dim=16, seed=0), x)
        true_i = np.argsort(sp_dist.cdist(q, x, "sqeuclidean"), 1)[:, :10]
        _, ic = ivf_pq.search(ivf_pq.SearchParams(n_probes=32), classic,
                              q, k=10)
        _, i4 = ivf_pq.search(
            ivf_pq.SearchParams(n_probes=32, funnel_widen=4), idx, q, k=10)
        rec_c = _recall(np.asarray(ic), true_i)
        rec_4 = _recall(np.asarray(i4), true_i)
        assert rec_4 >= rec_c - 0.05, (rec_4, rec_c)

    def test_inner_product_funnel(self, data):
        x, q = data
        base = dict(n_lists=16, pq_dim=16, metric="inner_product", seed=0)
        classic = ivf_pq.build(ivf_pq.IndexParams(**base), x)
        funnel = ivf_pq.build(
            ivf_pq.IndexParams(fast_scan="1bit", **base), x)
        true_i = np.argsort(-(q @ x.T), 1)[:, :10]
        _, ic = ivf_pq.search(ivf_pq.SearchParams(n_probes=32), classic,
                              q, k=10)
        _, if_ = ivf_pq.search(
            ivf_pq.SearchParams(n_probes=32, funnel_widen=8), funnel, q, k=10)
        rec_c = _recall(np.asarray(ic), true_i)
        rec_f = _recall(np.asarray(if_), true_i)
        assert rec_f >= rec_c - 0.1, (rec_f, rec_c)
        # width 1 stays bit-equal under IP too
        dc, ic1 = ivf_pq.search(ivf_pq.SearchParams(n_probes=8), classic,
                                q, k=5)
        df, if1 = ivf_pq.search(
            ivf_pq.SearchParams(n_probes=8, funnel_widen=1), funnel, q, k=5)
        np.testing.assert_array_equal(np.asarray(ic1), np.asarray(if1))
        np.testing.assert_array_equal(np.asarray(dc), np.asarray(df))

    def test_funnel_restrictions(self, twins, data):
        """Every invalid funnel combination fails loudly at the entry
        point, not as silent quality loss deep in the scan."""
        from raft_tpu.core import RaftError

        _, q = data
        classic, funnel = twins
        with pytest.raises(RaftError, match="fast[-_ ]?scan"):
            ivf_pq.search(ivf_pq.SearchParams(n_probes=8, funnel_widen=2),
                          classic, q, k=10)
        with pytest.raises(RaftError):
            ivf_pq.search(ivf_pq.SearchParams(n_probes=8, funnel_widen=0),
                          funnel, q, k=10)
        with pytest.raises(RaftError, match="tiled"):
            ivf_pq.search(
                ivf_pq.SearchParams(n_probes=8, funnel_widen=2,
                                    scan_order="grouped"), funnel, q, k=10)
        with pytest.raises(RaftError, match="one-hot|onehot"):
            ivf_pq.search(
                ivf_pq.SearchParams(n_probes=8, funnel_widen=2,
                                    scan_impl="select"), funnel, q, k=10)
        with pytest.raises(RaftError, match="int8"):
            ivf_pq.search(
                ivf_pq.SearchParams(n_probes=8, funnel_widen=2,
                                    lut_dtype="int8"), funnel, q, k=10)

    def test_extend_carries_sig(self, data):
        """extend() encodes signatures for the new rows through the same
        per-list scales — the grown twin stays bit-equal to a grown
        classic twin at width 1, and the widened funnel still serves."""
        x, q = data
        base = dict(n_lists=16, pq_dim=16, seed=0)
        ids = np.arange(5000, 6000, dtype=np.int32)
        f = ivf_pq.build(
            ivf_pq.IndexParams(fast_scan="1bit", **base), x[:5000])
        f = ivf_pq.extend(f, x[5000:], ids)
        c = ivf_pq.build(ivf_pq.IndexParams(**base), x[:5000])
        c = ivf_pq.extend(c, x[5000:], ids)
        assert f.size == 6000 and f.has_fast_scan
        assert f.list_sig.shape == (f.n_lists, f.capacity, 4)
        dc, ic = ivf_pq.search(ivf_pq.SearchParams(n_probes=8), c, q, k=10)
        df, if_ = ivf_pq.search(
            ivf_pq.SearchParams(n_probes=8, funnel_widen=1), f, q, k=10)
        np.testing.assert_array_equal(np.asarray(ic), np.asarray(if_))
        np.testing.assert_array_equal(np.asarray(dc), np.asarray(df))
        true_i = np.argsort(sp_dist.cdist(q, x, "sqeuclidean"), 1)[:, :10]
        _, iw = ivf_pq.search(
            ivf_pq.SearchParams(n_probes=32, funnel_widen=8), f, q, k=10)
        assert _recall(np.asarray(iw), true_i) > 0.3  # coarse-codec anchor

    def test_underfill_sentinels_funnel(self, data, check_filter_underfill):
        """Filtered candidates keep their -1/±inf sentinel through the
        binary stage, the PQ rerank and the final merge (same shared
        checker as the classic path)."""
        x, q = data
        idx = ivf_pq.build(
            ivf_pq.IndexParams(n_lists=16, pq_dim=16, fast_scan="1bit",
                               seed=0), x)
        alive = [44, 1023, 5020]
        keep = np.zeros(x.shape[0], bool)
        keep[alive] = True
        d, i = ivf_pq.search(
            ivf_pq.SearchParams(n_probes=64, funnel_widen=4), idx, q, 10,
            sample_filter=keep)
        check_filter_underfill(d, i, alive, select_min=True)

    def test_underfill_sentinels_funnel_inner_product(
            self, data, check_filter_underfill):
        x, q = data
        idx = ivf_pq.build(
            ivf_pq.IndexParams(n_lists=16, pq_dim=16, fast_scan="1bit",
                               metric="inner_product", seed=0), x)
        alive = [3, 997]
        keep = np.zeros(x.shape[0], bool)
        keep[alive] = True
        d, i = ivf_pq.search(
            ivf_pq.SearchParams(n_probes=64, funnel_widen=4), idx, q, 10,
            sample_filter=keep)
        check_filter_underfill(d, i, alive, select_min=False)

    def test_filter_fills_k_when_enough_survive(self, data,
                                                check_filter_underfill):
        """The other side of the underfill contract: with >= k survivors
        the funnel must FILL every slot from the alive set — a binary
        stage that silently narrowed the pool would leak sentinels here."""
        x, q = data
        idx = ivf_pq.build(
            ivf_pq.IndexParams(n_lists=16, pq_dim=16, fast_scan="1bit",
                               seed=0), x)
        alive = list(range(100, 140))  # 40 survivors >= k=10
        keep = np.zeros(x.shape[0], bool)
        keep[alive] = True
        d, i = ivf_pq.search(
            ivf_pq.SearchParams(n_probes=idx.n_lists, funnel_widen=8),
            idx, q, 10, sample_filter=keep)
        check_filter_underfill(d, i, alive, select_min=True)

    # -- serialize: raft_tpu/13 codec record, /12 read-compat ---------------

    def test_serialize_13_roundtrip(self, tmp_path, data):
        """The /13 codec record (rotation_kind, codebook_loss, fast_scan,
        list_sig, sig_scales) round-trips and the loaded funnel serves
        bit-equal at the widened point too."""
        x, q = data
        idx = ivf_pq.build(
            ivf_pq.IndexParams(n_lists=16, pq_dim=16, rotation="opq",
                               fast_scan="1bit", seed=0), x)
        p = str(tmp_path / "funnel13.bin")
        ivf_pq.save(idx, p)
        idx2 = ivf_pq.load(p)
        assert idx2.rotation_kind == "opq"
        assert idx2.fast_scan == "1bit" and idx2.has_fast_scan
        np.testing.assert_array_equal(np.asarray(idx.list_sig),
                                      np.asarray(idx2.list_sig))
        np.testing.assert_array_equal(np.asarray(idx.sig_scales),
                                      np.asarray(idx2.sig_scales))
        sp = ivf_pq.SearchParams(n_probes=8, funnel_widen=4)
        d1, i1 = ivf_pq.search(sp, idx, q, k=5)
        d2, i2 = ivf_pq.search(sp, idx2, q, k=5)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
        np.testing.assert_array_equal(np.asarray(d1), np.asarray(d2))

    def test_serialize_12_read_compat_both_directions(self, tmp_path, data,
                                                      monkeypatch):
        """(a) Bytes written by a writer PINNED to raft_tpu/12 (pre-codec
        layout) load in this build as a classic index — no tier, classic
        search bit-equal; (b) this build's /13 bytes of a NO-tier index
        read back classic too (the record is present but empty)."""
        from raft_tpu.core import RaftError
        from raft_tpu.core import serialize as core_serialize

        x, q = data
        idx = ivf_pq.build(
            ivf_pq.IndexParams(n_lists=16, pq_dim=16, fast_scan="1bit",
                               seed=0), x)
        old_path = str(tmp_path / "v12.bin")
        monkeypatch.setattr(core_serialize, "SERIALIZATION_VERSION",
                            "raft_tpu/12")
        ivf_pq.save(idx, old_path)
        monkeypatch.undo()
        assert core_serialize.version_number(
            core_serialize.SERIALIZATION_VERSION) >= 13
        old = ivf_pq.load(old_path)
        assert old.fast_scan == "none" and not old.has_fast_scan
        assert old.list_sig.shape == (old.n_lists, 0, 0)
        d1, i1 = ivf_pq.search(
            ivf_pq.SearchParams(n_probes=8, funnel_widen=1), idx, q, k=5)
        d2, i2 = ivf_pq.search(ivf_pq.SearchParams(n_probes=8), old, q, k=5)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
        np.testing.assert_array_equal(np.asarray(d1), np.asarray(d2))
        # the tier did NOT survive the /12 bytes: widening must refuse
        with pytest.raises(RaftError):
            ivf_pq.search(ivf_pq.SearchParams(n_probes=8, funnel_widen=4),
                          old, q, k=5)

        classic = ivf_pq.build(
            ivf_pq.IndexParams(n_lists=16, pq_dim=16, seed=0), x)
        new_path = str(tmp_path / "v13_no_tier.bin")
        ivf_pq.save(classic, new_path)
        back = ivf_pq.load(new_path)
        assert back.fast_scan == "none" and not back.has_fast_scan

    # -- OPQ rotation (funnel stage a) --------------------------------------

    def test_opq_rotation_orthonormal(self, data):
        x, _ = data
        idx = ivf_pq.build(
            ivf_pq.IndexParams(n_lists=16, pq_dim=16, rotation="opq",
                               seed=0), x)
        assert idx.rotation_kind == "opq"
        r = np.asarray(idx.rotation)
        np.testing.assert_allclose(r @ r.T, np.eye(r.shape[0]), atol=1e-4)

    def test_opq_recall_holds_baseline(self, data):
        """OPQ must never cost recall (it is a no-op by construction on
        isotropic data; blobs sit close to that regime)."""
        x, q = data
        true_i = np.argsort(sp_dist.cdist(q, x, "sqeuclidean"), 1)[:, :10]
        recs = {}
        for rot in ("none", "opq"):
            idx = ivf_pq.build(
                ivf_pq.IndexParams(n_lists=16, pq_dim=16, rotation=rot,
                                   seed=0), x)
            _, i = ivf_pq.search(ivf_pq.SearchParams(n_probes=16), idx,
                                 q, k=10)
            recs[rot] = _recall(np.asarray(i), true_i)
        assert recs["opq"] >= recs["none"] - 0.05, recs

    # -- anisotropic codebooks (funnel stage b) -----------------------------

    def test_anisotropic_ip_recall(self, data):
        """Score-aware codebooks target inner-product serving: recall at
        the IP operating point must hold the plain-loss baseline."""
        x, q = data
        true_i = np.argsort(-(q @ x.T), 1)[:, :10]
        recs = {}
        for loss in ("l2", "anisotropic"):
            idx = ivf_pq.build(
                ivf_pq.IndexParams(n_lists=16, pq_dim=16,
                                   metric="inner_product",
                                   codebook_loss=loss, seed=0), x)
            _, i = ivf_pq.search(ivf_pq.SearchParams(n_probes=16), idx,
                                 q, k=10)
            recs[loss] = _recall(np.asarray(i), true_i)
        assert recs["anisotropic"] >= recs["l2"] - 0.05, recs

    def test_anisotropic_rejects_split_pq8(self, data):
        """The split-pq8 codebook's two stages share one proxy EM — the
        anisotropic weighting cannot thread through it and must refuse."""
        from raft_tpu.core import RaftError

        with pytest.raises(RaftError, match="anisotropic"):
            ivf_pq.build(
                ivf_pq.IndexParams(n_lists=16, pq_dim=8, pq_bits=8,
                                   pq8_split=True,
                                   codebook_loss="anisotropic", seed=0),
                data[0])

    # -- stream embedding + tiered/sharded composition ----------------------

    @pytest.fixture(scope="class")
    def small_corpus(self):
        r = np.random.default_rng(7)
        X = r.standard_normal((2048, 16)).astype(np.float32)
        Q = r.standard_normal((32, 16)).astype(np.float32)
        return X, Q

    def test_stream_embedded_13_roundtrip(self, small_corpus, tmp_path):
        """A funnel index embedded in a stream file rides the /13 codec
        record: the reloaded sealed index keeps the tier and the widened
        funnel pin serves bit-equal."""
        from raft_tpu import stream

        X, Q = small_corpus
        params = ivf_pq.IndexParams(n_lists=32, pq_bits=4, pq_dim=8,
                                    fast_scan="1bit", seed=0)
        sp = ivf_pq.SearchParams(n_probes=8, funnel_widen=4)
        sealed = ivf_pq.build(params, X)
        m = stream.MutableIndex(sealed, search_params=sp,
                                index_params=params, dataset=X,
                                name="funnel13")
        path = str(tmp_path / "funnel13.stream")
        stream.save(m, path)
        rec = stream.load(path, search_params=sp)
        assert rec._state.sealed.has_fast_scan
        assert rec._state.sealed.fast_scan == "1bit"
        d1, i1 = m.search(Q, 10)
        d2, i2 = rec.search(Q, 10)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
        np.testing.assert_array_equal(np.asarray(d1), np.asarray(d2))

    def test_wrap_refuses_funnel_pin_without_tier(self, small_corpus):
        """The funnel/tier mismatch fails at WRAP time, not on a serving
        thread mid-request."""
        from raft_tpu import stream
        from raft_tpu.core import RaftError

        X, _ = small_corpus
        params = ivf_pq.IndexParams(n_lists=32, pq_bits=4, pq_dim=8, seed=0)
        sealed = ivf_pq.build(params, X)
        with pytest.raises(RaftError, match="fast[-_ ]?scan"):
            stream.MutableIndex(
                sealed, search_params=ivf_pq.SearchParams(n_probes=8,
                                                          funnel_widen=4),
                index_params=params, dataset=X, name="funnel_guard")

    def test_tiered_composition_width1_bit_equal(self, small_corpus):
        """ISSUE 16 acceptance: the funnel index under tiered storage at
        width 1 answers bit-equal (ids AND distances) to the all-HBM
        classic-PQ twin — composition changes placement, never answers."""
        from raft_tpu import stream

        X, Q = small_corpus
        base = dict(n_lists=32, pq_bits=4, pq_dim=8, seed=0)
        classic = ivf_pq.build(ivf_pq.IndexParams(**base), X)
        funnel = ivf_pq.build(
            ivf_pq.IndexParams(fast_scan="1bit", **base), X)
        a = stream.MutableIndex(
            classic, search_params=ivf_pq.SearchParams(n_probes=8),
            index_params=ivf_pq.IndexParams(**base), dataset=X,
            storage="hbm", name="cmp_hbm_classic")
        b = stream.MutableIndex(
            funnel,
            search_params=ivf_pq.SearchParams(n_probes=8, funnel_widen=1),
            index_params=ivf_pq.IndexParams(fast_scan="1bit", **base),
            dataset=X, storage="tiered",
            tier=stream.TierPolicy(oracle_chunk=512, auto_promote=False),
            name="cmp_tiered_funnel")
        da, ia = a.search(Q, 10)
        db, ib = b.search(Q, 10)
        np.testing.assert_array_equal(np.asarray(ia), np.asarray(ib))
        np.testing.assert_array_equal(np.asarray(da), np.asarray(db))
        dra, ira = a.search_refined(Q, 10, 4)
        drb, irb = b.search_refined(Q, 10, 4)
        np.testing.assert_array_equal(np.asarray(ira), np.asarray(irb))
        np.testing.assert_array_equal(np.asarray(dra), np.asarray(drb))

    def test_sharded_composition_width1_bit_equal(self, small_corpus):
        """The sharded half of the composition acceptance: per-shard
        funnel builds at width 1 scatter-gather to the same ids as the
        classic-build sharded twin."""
        from raft_tpu import stream

        X, Q = small_corpus
        base = dict(n_lists=8, pq_bits=4, pq_dim=8, seed=0)
        a = stream.ShardedMutableIndex(
            X, n_shards=2,
            build=lambda x: ivf_pq.build(ivf_pq.IndexParams(**base), x),
            search_params=ivf_pq.SearchParams(n_probes=8),
            name="shard_classic")
        b = stream.ShardedMutableIndex(
            X, n_shards=2,
            build=lambda x: ivf_pq.build(
                ivf_pq.IndexParams(fast_scan="1bit", **base), x),
            search_params=ivf_pq.SearchParams(n_probes=8, funnel_widen=1),
            name="shard_funnel")
        da, ia = a.search(Q, 10)
        db, ib = b.search(Q, 10)
        np.testing.assert_array_equal(np.asarray(ia), np.asarray(ib))
        np.testing.assert_array_equal(np.asarray(da), np.asarray(db))


def test_funnel_sweep_1m_opq():
    """Heavy 1M funnel sweep (slow manifest; the ISSUE 16 capacity bar at
    the 1M recall anchor): an OPQ+1bit index swept over tune.funnel_grid
    must pin a widened operating point that holds the classic anchor,
    with the recall-vs-QPS frontier in the decision evidence, at >= 2x
    rows per hot-scan HBM byte."""
    from raft_tpu import tune
    from raft_tpu.neighbors import brute_force

    n, d, k = 1_000_000, 32, 10
    x, _ = make_blobs(n, d, n_clusters=1000, cluster_std=1.0, seed=9)
    x = np.asarray(x)
    q = x[:256]
    _, gt = brute_force.knn(x, q, k)
    idx = ivf_pq.build(
        ivf_pq.IndexParams(n_lists=1024, pq_bits=4, pq_dim=16,
                           rotation="opq", fast_scan="1bit", seed=0,
                           kmeans_batch_rows=8192), x)
    assert idx.has_fast_scan and idx.rotation_kind == "opq"
    log = tune.DecisionLog()
    dec = tune.sweep(idx, q, k=k, dataset=x, gt=np.asarray(gt),
                     grid=tune.funnel_grid(), recall_target="default",
                     repeats=1, log=log)
    ev = dec.evidence
    assert ev["target_met"], ev
    assert len(ev["trials"]) >= 5 and ev["frontier"], ev
    # the hot-scan capacity bar: classic streams pq_dim+4 B/row, the
    # funnel sig_words+4 (1bit at d_rot=32 -> 4 packed bytes)
    bpr_classic = int(idx.list_codes.shape[2]) + 4
    bpr_funnel = int(idx.list_sig.shape[2]) + 4
    assert bpr_classic / bpr_funnel >= 2.0, (bpr_classic, bpr_funnel)
