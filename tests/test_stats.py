"""Stats tests vs numpy/sklearn (reference analogue: cpp/test/stats/*, STATS_TEST)."""

import numpy as np
import pytest
from sklearn import metrics as skm

from raft_tpu import stats


class TestMoments:
    def test_mean_stddev(self, rng):
        m = rng.standard_normal((50, 6)).astype(np.float32)
        np.testing.assert_allclose(np.asarray(stats.mean(m)), m.mean(0), atol=1e-5)
        np.testing.assert_allclose(np.asarray(stats.stddev(m)), m.std(0, ddof=1), rtol=1e-4)

    def test_meanvar(self, rng):
        m = rng.standard_normal((50, 6)).astype(np.float32)
        mu, var = stats.meanvar(m)
        np.testing.assert_allclose(np.asarray(var), m.var(0, ddof=1), rtol=1e-4)

    def test_cov(self, rng):
        m = rng.standard_normal((100, 4)).astype(np.float32)
        np.testing.assert_allclose(np.asarray(stats.cov(m)), np.cov(m.T), rtol=1e-3, atol=1e-4)

    def test_minmax_sum(self, rng):
        m = rng.standard_normal((20, 3)).astype(np.float32)
        lo, hi = stats.minmax(m)
        np.testing.assert_array_equal(np.asarray(lo), m.min(0))
        np.testing.assert_array_equal(np.asarray(hi), m.max(0))
        np.testing.assert_allclose(np.asarray(stats.sum_(m)), m.sum(0), rtol=1e-4, atol=1e-5)

    def test_histogram(self, rng):
        m = rng.random((200, 2)).astype(np.float32)
        h = np.asarray(stats.histogram(m, n_bins=10, lower=0.0, upper=1.0))
        assert h.shape == (10, 2)
        assert h.sum(0).tolist() == [200, 200]
        want0 = np.histogram(m[:, 0], bins=10, range=(0, 1))[0]
        np.testing.assert_array_equal(h[:, 0], want0)

    def test_weighted_mean(self, rng):
        m = rng.random((30, 4)).astype(np.float32)
        w = rng.random(30).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(stats.weighted_mean(m, w)), np.average(m, axis=0, weights=w), rtol=1e-4
        )

    def test_mean_center_roundtrip(self, rng):
        m = rng.random((10, 4)).astype(np.float32)
        mu = m.mean(0)
        c = stats.mean_center(m)
        np.testing.assert_allclose(np.asarray(c).mean(0), 0.0, atol=1e-6)
        np.testing.assert_allclose(np.asarray(stats.mean_add(c, mu)), m, atol=1e-5)


class TestClassification:
    def test_accuracy(self):
        assert float(stats.accuracy([1, 2, 3, 4], [1, 2, 0, 4])) == pytest.approx(0.75)

    def test_r2(self, rng):
        y = rng.random(50)
        yh = y + 0.1 * rng.standard_normal(50)
        np.testing.assert_allclose(float(stats.r2_score(y, yh)), skm.r2_score(y, yh), atol=1e-4)

    def test_regression_metrics(self, rng):
        p = rng.random(40)
        r = rng.random(40)
        mae, mse, medae = stats.regression_metrics(p, r)
        np.testing.assert_allclose(float(mae), np.abs(p - r).mean(), rtol=1e-5)
        np.testing.assert_allclose(float(mse), ((p - r) ** 2).mean(), rtol=1e-5)
        np.testing.assert_allclose(float(medae), np.median(np.abs(p - r)), rtol=1e-5)


class TestClusterMetrics:
    def setup_method(self, _):
        r = np.random.default_rng(0)
        self.a = r.integers(0, 4, 200)
        self.b = np.where(r.random(200) < 0.8, self.a, r.integers(0, 4, 200))

    def test_contingency(self):
        c = np.asarray(stats.contingency_matrix(self.a, self.b, 4, 4))
        assert c.sum() == 200
        want = skm.cluster.contingency_matrix(self.a, self.b)
        np.testing.assert_array_equal(c, want)

    def test_entropy(self):
        got = float(stats.entropy(self.a, 4))
        p = np.bincount(self.a, minlength=4) / 200
        want = -(p[p > 0] * np.log(p[p > 0])).sum()
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_mutual_info(self):
        np.testing.assert_allclose(
            float(stats.mutual_info_score(self.a, self.b, 4)),
            skm.mutual_info_score(self.a, self.b),
            atol=1e-5,
        )

    def test_rand_index(self):
        # unadjusted RI vs sklearn's pair_confusion-based value
        from sklearn.metrics.cluster import pair_confusion_matrix

        pc = pair_confusion_matrix(self.a, self.b)
        want = (pc[0, 0] + pc[1, 1]) / pc.sum()
        np.testing.assert_allclose(float(stats.rand_index(self.a, self.b)), want, atol=1e-5)

    def test_ari(self):
        np.testing.assert_allclose(
            float(stats.adjusted_rand_index(self.a, self.b)),
            skm.adjusted_rand_score(self.a, self.b),
            atol=1e-5,
        )

    def test_homogeneity_completeness_v(self):
        h, c, v = (
            float(stats.homogeneity_score(self.a, self.b, 4)),
            float(stats.completeness_score(self.a, self.b, 4)),
            float(stats.v_measure(self.a, self.b, 4)),
        )
        hs, cs, vs = skm.homogeneity_completeness_v_measure(self.a, self.b)
        np.testing.assert_allclose([h, c, v], [hs, cs, vs], atol=1e-4)

    def test_silhouette(self, rng):
        from raft_tpu.random import make_blobs

        x, labels = make_blobs(300, 5, n_clusters=3, cluster_std=0.5, seed=3)
        x, labels = np.asarray(x), np.asarray(labels)
        got = float(stats.silhouette_score(x, labels, 3))
        want = skm.silhouette_score(x, labels)
        np.testing.assert_allclose(got, want, atol=1e-3)

    def test_kl_divergence(self, rng):
        p = rng.random(20)
        p /= p.sum()
        q = rng.random(20)
        q /= q.sum()
        want = (p * np.log(p / q)).sum()
        np.testing.assert_allclose(float(stats.kl_divergence(p, q)), want, rtol=1e-4)

    def test_trustworthiness(self, rng):
        from sklearn.manifold import trustworthiness as sk_trust

        x = rng.standard_normal((60, 8)).astype(np.float32)
        e = x[:, :2] + 0.01 * rng.standard_normal((60, 2)).astype(np.float32)
        got = float(stats.trustworthiness(x, e, n_neighbors=5))
        want = sk_trust(x, e, n_neighbors=5)
        np.testing.assert_allclose(got, want, atol=1e-2)

    def test_dispersion(self):
        centroids = np.array([[0.0, 0.0], [2.0, 0.0]], np.float32)
        sizes = np.array([10, 10], np.float32)
        # global centroid (1,0); each centroid at squared distance 1 → sqrt(20)
        np.testing.assert_allclose(float(stats.dispersion(centroids, sizes)), np.sqrt(20), rtol=1e-5)

    def test_information_criterion(self):
        ll = -100.0
        np.testing.assert_allclose(float(stats.information_criterion(ll, 5, 50, "aic")), 210.0)
        np.testing.assert_allclose(
            float(stats.information_criterion(ll, 5, 50, "bic")), 200 + 5 * np.log(50), rtol=1e-6
        )
