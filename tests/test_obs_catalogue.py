"""Metrics-catalogue drift lint (tier-1, ISSUE 7 satellite).

Every ``raft_tpu_*`` metric registered anywhere in the source tree must
appear in docs/observability.md's catalogue table, and every catalogued
name must still be registered in source — both directions, so the
catalogue can no longer silently rot (new metrics shipping undocumented,
or doc rows surviving their metric's removal).

The source side is a static scan for the registration idiom
(``counter("raft_tpu_...")`` / ``gauge(...)`` / ``histogram(...)`` with a
literal first argument) — the registry offers no other way to create a
metric, and a dynamically-composed name would defeat grepability on
purpose, so the lint also enforces the literal-name convention.
"""

import pathlib
import re

REPO = pathlib.Path(__file__).resolve().parents[1]
DOC = REPO / "docs" / "observability.md"

# registration call with a literal raft_tpu_* name (possibly wrapped to
# the next line); \s* spans newlines
_REGISTRATION = re.compile(
    r'\b(?:counter|gauge|histogram)\(\s*"(raft_tpu_[a-z0-9_]+)"')
# a catalogue row: "| `raft_tpu_...` | type | ..."
_DOC_ROW = re.compile(r"^\|\s*`(raft_tpu_[a-z0-9_]+)`\s*\|", re.M)


def _source_metrics() -> set:
    names = set()
    for path in sorted((REPO / "raft_tpu").rglob("*.py")):
        names.update(_REGISTRATION.findall(path.read_text()))
    return names


def _documented_metrics() -> set:
    return set(_DOC_ROW.findall(DOC.read_text()))


def test_every_registered_metric_is_documented():
    undocumented = _source_metrics() - _documented_metrics()
    assert not undocumented, (
        "metrics registered in source but missing from the "
        f"docs/observability.md catalogue table: {sorted(undocumented)}")


def test_every_documented_metric_is_registered():
    stale = _documented_metrics() - _source_metrics()
    assert not stale, (
        "docs/observability.md catalogues metrics no source file "
        f"registers: {sorted(stale)}")


def test_scan_is_not_vacuous():
    """The lint must actually see both sides (a regex gone stale would
    pass the two set assertions with empty sets)."""
    src, doc = _source_metrics(), _documented_metrics()
    assert len(src) >= 30, sorted(src)
    assert len(doc) >= 30, sorted(doc)
    # spot-check well-known names from three subsystems
    for name in ("raft_tpu_serve_queue_wait_seconds",
                 "raft_tpu_tune_trials_total",
                 "raft_tpu_compile_cache_total"):
        assert name in src and name in doc, name
