"""Metrics-catalogue drift lint (tier-1, ISSUE 7 satellite).

Every ``raft_tpu_*`` metric registered anywhere in the source tree must
appear in docs/observability.md's catalogue table, and every catalogued
name must still be registered in source — both directions, so the
catalogue can no longer silently rot (new metrics shipping undocumented,
or doc rows surviving their metric's removal).

The source side is a static scan for the registration idiom
(``counter("raft_tpu_...")`` / ``gauge(...)`` / ``histogram(...)`` with a
literal first argument) — the registry offers no other way to create a
metric, and a dynamically-composed name would defeat grepability on
purpose, so the lint also enforces the literal-name convention.
"""

import pathlib
import re

REPO = pathlib.Path(__file__).resolve().parents[1]
DOC = REPO / "docs" / "observability.md"

# registration call with a literal raft_tpu_* name (possibly wrapped to
# the next line); \s* spans newlines
_REGISTRATION = re.compile(
    r'\b(?:counter|gauge|histogram)\(\s*"(raft_tpu_[a-z0-9_]+)"')
# a catalogue row: "| `raft_tpu_...` | type | ..."
_DOC_ROW = re.compile(r"^\|\s*`(raft_tpu_[a-z0-9_]+)`\s*\|", re.M)


def _source_metrics() -> set:
    names = set()
    for path in sorted((REPO / "raft_tpu").rglob("*.py")):
        names.update(_REGISTRATION.findall(path.read_text()))
    return names


def _documented_metrics() -> set:
    return set(_DOC_ROW.findall(DOC.read_text()))


def test_every_registered_metric_is_documented():
    undocumented = _source_metrics() - _documented_metrics()
    assert not undocumented, (
        "metrics registered in source but missing from the "
        f"docs/observability.md catalogue table: {sorted(undocumented)}")


def test_every_documented_metric_is_registered():
    stale = _documented_metrics() - _source_metrics()
    assert not stale, (
        "docs/observability.md catalogues metrics no source file "
        f"registers: {sorted(stale)}")


def test_scan_is_not_vacuous():
    """The lint must actually see both sides (a regex gone stale would
    pass the two set assertions with empty sets)."""
    src, doc = _source_metrics(), _documented_metrics()
    assert len(src) >= 30, sorted(src)
    assert len(doc) >= 30, sorted(doc)
    # spot-check well-known names from three subsystems
    for name in ("raft_tpu_serve_queue_wait_seconds",
                 "raft_tpu_tune_trials_total",
                 "raft_tpu_compile_cache_total"):
        assert name in src and name in doc, name


# ---------------------------------------------------------------------------
# event-kind catalogue (ISSUE 17 satellite): KINDS <-> docs, both ways
# ---------------------------------------------------------------------------

# a documented kind row between the markers: "| `kind` | `severity` | ..."
# (kind names may be namespaced with "/" — the control plane's
# ``control/*`` family)
_KIND_ROW = re.compile(
    r"^\|\s*`([a-z0-9_/]+)`\s*\|\s*`?(info|warning|error)`?", re.M)
# a literal emit call site: emit("kind" / obs_events.emit(\n    "kind"
_EMIT_SITE = re.compile(r'\bemit\(\s*\n?\s*"([a-z0-9_/]+)"')


def _documented_kinds() -> dict:
    text = DOC.read_text()
    start = text.index("<!-- event-kind-catalogue:start -->")
    end = text.index("<!-- event-kind-catalogue:end -->")
    return dict(_KIND_ROW.findall(text[start:end]))


def _source_kinds() -> dict:
    from raft_tpu.obs.events import KINDS

    return dict(KINDS)


def test_every_event_kind_is_documented():
    src, doc = _source_kinds(), _documented_kinds()
    undocumented = set(src) - set(doc)
    assert not undocumented, (
        "event kinds in raft_tpu.obs.events.KINDS but missing from the "
        f"docs/observability.md kind catalogue: {sorted(undocumented)}")
    wrong = {k for k in src if doc[k] != src[k]}
    assert not wrong, (
        "documented default severity disagrees with KINDS for: "
        f"{sorted(wrong)}")


def test_every_documented_event_kind_exists():
    stale = set(_documented_kinds()) - set(_source_kinds())
    assert not stale, (
        "docs/observability.md catalogues event kinds KINDS no longer "
        f"defines: {sorted(stale)}")


def test_every_event_kind_has_a_literal_emit_site():
    """Every kind in the catalogue is actually emitted somewhere, with a
    literal kind string (same grepability convention as metric names).
    ``flight_recorder`` is the journal's own breadcrumb — its emit site
    lives in events.py itself and counts like any other."""
    sites = set()
    for path in sorted((REPO / "raft_tpu").rglob("*.py")):
        sites.update(_EMIT_SITE.findall(path.read_text()))
    dead = set(_source_kinds()) - sites
    assert not dead, (
        f"KINDS entries with no literal emit(...) call site: {sorted(dead)}"
        " — either wire the call site or drop the kind")


def test_kind_scan_is_not_vacuous():
    src, doc = _source_kinds(), _documented_kinds()
    assert len(src) >= 20 and len(doc) >= 20, (len(src), len(doc))
    for kind in ("retune_advised", "reshard_advised", "replica_fenced",
                 "slo_verdict", "control/decision"):
        assert kind in src and kind in doc, kind


def test_advisory_and_transition_metrics_ride_the_journal():
    """ISSUE 17 satellite: every file registering an advisory/transition
    metric (``raft_tpu_*_advised*``, fence/failover/spill/refusal
    counters) must emit through the unified journal — a new advisory
    surface cannot ship outside the event plane."""
    transition_pat = re.compile(
        r"raft_tpu_[a-z0-9_]*(?:_advised|_fenced|_failovers?|_refusals?|"
        r"_spills?|_truncations?)_total")
    offenders = []
    for path in sorted((REPO / "raft_tpu").rglob("*.py")):
        text = path.read_text()
        if transition_pat.search(text) and "obs_events.emit(" not in text \
                and path.name != "events.py":
            offenders.append(str(path.relative_to(REPO)))
    assert not offenders, (
        "files register advisory/transition metrics but never emit to "
        f"the event journal: {offenders}")
    # not vacuous: the known advisory sites must be in scope of the scan
    scanned = {p.name for p in (REPO / "raft_tpu").rglob("*.py")
               if transition_pat.search(p.read_text())}
    assert {"quality.py", "compactor.py", "replicated.py"} <= scanned, \
        sorted(scanned)
