"""Ball cover + sample filter + legacy spatial API tests.

Analogue of cpp/test/neighbors/ball_cover.cu (exactness vs brute force) and
the filtered-search coverage in cpp/test/neighbors/ann_ivf_flat.cuh.
"""

import numpy as np
import pytest

from raft_tpu.neighbors import BitsetFilter, ball_cover, ivf_flat, knn
from raft_tpu.spatial import approx_knn_build_index, approx_knn_search


def _brute(x, q, k):
    d2 = ((q[:, None, :].astype(np.float64) - x[None, :, :]) ** 2).sum(-1)
    idx = np.argsort(d2, axis=1)[:, :k]
    return np.take_along_axis(d2, idx, axis=1), idx


def test_ball_cover_exact_small(rng):
    n, d, m, k = 500, 8, 40, 7
    x = rng.random((n, d)).astype(np.float32)
    q = rng.random((m, d)).astype(np.float32)
    index = ball_cover.build(x, metric="sqeuclidean", seed=3)
    dists, idx = ball_cover.knn_query(index, q, k)
    dists, idx = np.asarray(dists), np.asarray(idx)
    want_d, _ = _brute(x, q, k)
    # exactness: distances must match brute force (ids may tie-swap)
    np.testing.assert_allclose(np.sort(dists, 1), np.sort(want_d, 1), atol=1e-3, rtol=1e-3)


def test_ball_cover_all_knn(rng):
    n, d, k = 300, 6, 5
    x = rng.random((n, d)).astype(np.float32)
    index = ball_cover.build(x, seed=1)
    dists, idx = ball_cover.all_knn_query(index, k)
    dists, idx = np.asarray(dists), np.asarray(idx)
    want_d, _ = _brute(x, x, k)
    np.testing.assert_allclose(np.sort(dists, 1), np.sort(want_d, 1), atol=1e-3, rtol=1e-3)
    # nearest neighbor of each point is itself
    assert (np.sort(dists, 1)[:, 0] < 1e-6).all()


def test_ball_cover_haversine(rng):
    n, m, k = 400, 20, 4
    x = np.stack([rng.uniform(-1.2, 1.2, n), rng.uniform(-3, 3, n)], 1).astype(np.float32)
    q = np.stack([rng.uniform(-1.2, 1.2, m), rng.uniform(-3, 3, m)], 1).astype(np.float32)
    index = ball_cover.build(x, metric="haversine", seed=2)
    dists, _ = ball_cover.knn_query(index, q, k)
    dists = np.asarray(dists)

    def hav(a, b):
        s1 = np.sin(0.5 * (b[:, 0] - a[0]))
        s2 = np.sin(0.5 * (b[:, 1] - a[1]))
        return 2 * np.arcsin(np.sqrt(np.clip(s1**2 + np.cos(a[0]) * np.cos(b[:, 0]) * s2**2, 0, 1)))

    for i in range(m):
        want = np.sort(hav(q[i].astype(np.float64), x))[:k]
        np.testing.assert_allclose(np.sort(dists[i]), want, atol=1e-4)


def test_ball_cover_eps_nn(rng):
    n, m = 250, 15
    x = rng.random((n, 4)).astype(np.float32)
    q = rng.random((m, 4)).astype(np.float32)
    eps = 0.35
    index = ball_cover.build(x, seed=4)
    adj, vd = ball_cover.eps_nn_query(index, q, eps)
    adj = np.asarray(adj)
    d = np.sqrt(((q[:, None, :].astype(np.float64) - x[None, :, :]) ** 2).sum(-1))
    want = d <= eps
    np.testing.assert_array_equal(adj, want)
    np.testing.assert_array_equal(np.asarray(vd)[:-1], want.sum(1))


def test_ball_cover_clustered_exactness(rng):
    # adversarial layout: tight clusters + one far-flung wide cluster whose
    # landmark ranks below the probed set by center distance but is flagged by
    # the triangle-inequality lower bound (post-filter membership regression)
    c1 = rng.normal(0, 0.05, (150, 4)).astype(np.float32)
    c2 = rng.normal(2, 0.05, (150, 4)).astype(np.float32) + np.array([3, 0, 0, 0], np.float32)
    wide = (rng.normal(0, 2.5, (60, 4)) + np.array([1.5, 0, 0, 0])).astype(np.float32)
    x = np.concatenate([c1, c2, wide])
    q = rng.normal(1.5, 1.0, (25, 4)).astype(np.float32)
    index = ball_cover.build(x, n_landmarks=12, seed=7)
    dists, _ = ball_cover.knn_query(index, q, 6)
    want_d, _ = _brute(x, q, 6)
    np.testing.assert_allclose(np.sort(np.asarray(dists), 1), np.sort(want_d, 1), atol=1e-3, rtol=1e-3)


def test_ball_cover_eps_nn_haversine(rng):
    n, m = 200, 10
    x = np.stack([rng.uniform(-1.2, 1.2, n), rng.uniform(-3, 3, n)], 1).astype(np.float32)
    q = np.stack([rng.uniform(-1.2, 1.2, m), rng.uniform(-3, 3, m)], 1).astype(np.float32)
    index = ball_cover.build(x, metric="haversine", seed=5)
    adj, _ = ball_cover.eps_nn_query(index, q, eps=0.5)

    def hav(a, b):
        s1 = np.sin(0.5 * (b[:, 0] - a[0]))
        s2 = np.sin(0.5 * (b[:, 1] - a[1]))
        return 2 * np.arcsin(np.sqrt(np.clip(s1**2 + np.cos(a[0]) * np.cos(b[:, 0]) * s2**2, 0, 1)))

    want = np.stack([hav(q[i].astype(np.float64), x) <= 0.5 for i in range(m)])
    np.testing.assert_array_equal(np.asarray(adj), want)


def test_filter_underfill_returns_sentinel(rng):
    # fewer kept rows than k: excluded ids must NOT appear — slots are -1
    n, m, k = 50, 4, 8
    x = rng.random((n, 6)).astype(np.float32)
    q = rng.random((m, 6)).astype(np.float32)
    keep = np.zeros(n, bool)
    keep[:3] = True  # only 3 candidates for k=8
    dists, idx = knn(x, q, k, sample_filter=BitsetFilter(keep))
    idx = np.asarray(idx)
    valid = idx >= 0
    assert valid.sum(axis=1).tolist() == [3] * m
    assert keep[idx[valid]].all()
    assert np.isinf(np.asarray(dists)[~valid]).all()


def test_bitset_filter_brute_force(rng):
    n, m, k = 200, 10, 5
    x = rng.random((n, 16)).astype(np.float32)
    q = rng.random((m, 16)).astype(np.float32)
    keep = rng.random(n) > 0.5
    dists, idx = knn(x, q, k, sample_filter=BitsetFilter(keep))
    idx = np.asarray(idx)
    assert keep[idx].all(), "filtered candidates leaked into results"
    # equals brute force over the kept subset
    sub = np.where(keep)[0]
    want_d, want_i = _brute(x[sub], q, k)
    np.testing.assert_allclose(np.sort(np.asarray(dists), 1), np.sort(want_d, 1), atol=1e-3, rtol=1e-3)


def test_bitset_filter_ivf_flat(rng):
    n, m, k = 600, 12, 6
    x = rng.random((n, 10)).astype(np.float32)
    q = rng.random((m, 10)).astype(np.float32)
    keep = rng.random(n) > 0.3
    index = ivf_flat.build(ivf_flat.IndexParams(n_lists=16, seed=0), x)
    params = ivf_flat.SearchParams(n_probes=16)  # probe everything → exact
    _, idx = ivf_flat.search(params, index, q, k, sample_filter=keep)
    idx = np.asarray(idx)
    valid = idx >= 0
    assert keep[idx[valid]].all(), "filtered candidates leaked into IVF results"


def test_legacy_approx_knn(rng):
    n, m, k = 800, 30, 8
    x = rng.random((n, 16)).astype(np.float32)
    q = rng.random((m, 16)).astype(np.float32)
    index = approx_knn_build_index(ivf_flat.IndexParams(n_lists=20, seed=0), x)
    _, idx = approx_knn_search(index, q, k, n_probes=20)
    _, want_i = _brute(x, q, k)
    recall = np.mean([
        len(set(np.asarray(idx)[i]) & set(want_i[i])) / k for i in range(m)
    ])
    assert recall > 0.99
