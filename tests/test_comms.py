"""Communicator + distributed algorithm tests on the 8-device virtual mesh.

Analogue of the reference's raft-dask comms suite
(python/raft-dask/raft_dask/test/test_comms.py over LocalCUDACluster; the
on-device assertions mirror comms/detail/test.hpp) — per SURVEY.md §4 the
8-device CPU platform stands in for the multi-chip mesh.
"""

import numpy as np
from jax.sharding import PartitionSpec as P
import pytest
from scipy.spatial import distance as sp_dist

from raft_tpu.comms import Comms, test_utils
from raft_tpu import parallel
from raft_tpu.cluster import KMeansParams


@pytest.fixture(scope="module")
def comms(request):
    import jax
    from jax.sharding import Mesh

    devs = jax.devices()
    assert len(devs) >= 8
    return Comms(Mesh(np.array(devs[:8]), ("data",)), "data")


class TestCollectives:
    """perform_test_comms_* battery (comms_utils.pyx:78-244 analogue)."""

    def test_allreduce(self, comms):
        assert test_utils.test_collective_allreduce(comms)

    def test_broadcast(self, comms):
        assert test_utils.test_collective_broadcast(comms)

    def test_reduce(self, comms):
        assert test_utils.test_collective_reduce(comms)

    def test_allgather(self, comms):
        assert test_utils.test_collective_allgather(comms)

    def test_reducescatter(self, comms):
        assert test_utils.test_collective_reducescatter(comms)

    def test_p2p_ring(self, comms):
        assert test_utils.test_pointtopoint_ring(comms)

    def test_run_all(self, comms):
        results = test_utils.run_all(comms)
        assert all(results.values()), results

    def test_commsplit_2d(self):
        import jax
        from jax.sharding import Mesh

        devs = np.array(jax.devices()[:8]).reshape(4, 2)
        mesh = Mesh(devs, ("row", "col"))
        comms = Comms(mesh, "row")
        assert test_utils.test_commsplit(comms, "col")

    def test_size(self, comms):
        assert comms.size() == 8


class TestDistributedKnn:
    def test_matches_single_device(self, comms, rng):
        x = rng.random((800, 16)).astype(np.float32)
        q = rng.random((25, 16)).astype(np.float32)
        d_dist, i_dist = parallel.knn.knn(comms, x, q, k=10)
        full = sp_dist.cdist(q, x, "sqeuclidean")
        want_d = np.sort(full, axis=1)[:, :10]
        np.testing.assert_allclose(np.asarray(d_dist), want_d, atol=1e-3, rtol=1e-4)
        got_d = np.take_along_axis(full, np.asarray(i_dist), 1)
        np.testing.assert_allclose(got_d, want_d, atol=1e-3, rtol=1e-4)

    def test_non_divisible_self_pads(self, comms, rng):
        """n % size != 0 no longer raises (VERDICT r3 #6): the tail shard is
        padded with masked rows internally and results match single-device."""
        x = rng.random((805, 16)).astype(np.float32)  # 805 % 8 != 0
        q = rng.random((25, 16)).astype(np.float32)
        d_dist, i_dist = parallel.knn.knn(comms, x, q, k=10)
        full = sp_dist.cdist(q, x, "sqeuclidean")
        want_d = np.sort(full, axis=1)[:, :10]
        np.testing.assert_allclose(np.asarray(d_dist), want_d, atol=1e-3, rtol=1e-4)
        ids = np.asarray(i_dist)
        assert ids.min() >= 0 and ids.max() < 805  # no padded row leaks

    def test_k_must_fit_one_shard(self, comms, rng):
        from raft_tpu.core import RaftError

        with pytest.raises(RaftError, match="per-shard"):
            parallel.knn.knn(comms, np.zeros((16, 4), np.float32),
                             np.zeros((2, 4), np.float32), 3)

    def test_fused_local_kernel_interpret(self, comms, rng, monkeypatch):
        """The per-shard local search routes through the fused Pallas kernel
        when shapes qualify (VERDICT r3 #6 — the docstring's 'MXU GEMM +
        fused top-k' must be real); interpret mode stands in for Mosaic on
        the CPU test platform."""
        from raft_tpu.distance.types import DistanceType
        from raft_tpu.neighbors import brute_force as bf

        monkeypatch.setenv("RAFT_TPU_FUSED_KNN_INTERPRET", "1")
        assert bf._fused_eligible(DistanceType.L2Expanded, 10, 4096, 64,
                                  "exact", "float32")
        x = rng.random((8 * 4096, 64)).astype(np.float32)
        q = rng.random((16, 64)).astype(np.float32)
        d_dist, i_dist = parallel.knn.knn(comms, x, q, k=10)
        full = sp_dist.cdist(q, x, "sqeuclidean")
        want = np.sort(full, 1)[:, :10]
        np.testing.assert_allclose(np.asarray(d_dist), want, rtol=1e-4, atol=1e-3)


class TestDistributedKMeans:
    def test_recovers_blobs(self, comms):
        from raft_tpu.random import make_blobs
        from sklearn.metrics import adjusted_rand_score

        x, true_labels = make_blobs(1600, 8, n_clusters=4, cluster_std=0.3, seed=3)
        out = parallel.kmeans.fit(comms, KMeansParams(n_clusters=4, seed=0), np.asarray(x))
        assert out.centroids.shape == (4, 8)
        ari = adjusted_rand_score(np.asarray(true_labels), np.asarray(out.labels))
        assert ari > 0.95, ari

    def test_matches_single_device_inertia(self, comms):
        from raft_tpu.cluster import kmeans as kmeans_single
        from raft_tpu.random import make_blobs

        x, _ = make_blobs(1600, 8, n_clusters=4, cluster_std=0.3, seed=3)
        x = np.asarray(x)
        out_d = parallel.kmeans.fit(comms, KMeansParams(n_clusters=4, seed=0), x)
        out_s = kmeans_single.fit(KMeansParams(n_clusters=4, seed=0), x)
        # different inits, same optimum on well-separated blobs
        np.testing.assert_allclose(float(out_d.inertia), float(out_s.inertia), rtol=0.05)

    def test_distributed_predict(self, comms):
        from raft_tpu.random import make_blobs

        x, _ = make_blobs(800, 6, n_clusters=3, cluster_std=0.2, seed=1)
        x = np.asarray(x)
        out = parallel.kmeans.fit(comms, KMeansParams(n_clusters=3, seed=0), x)
        labels, inertia = parallel.kmeans.predict(comms, x, out.centroids)
        d = ((x[:, None, :] - np.asarray(out.centroids)[None]) ** 2).sum(-1)
        np.testing.assert_array_equal(np.asarray(labels), d.argmin(1))


class TestDistributedIvf:
    def test_matches_full_probe_recall(self, comms, rng):
        from raft_tpu.neighbors import ivf_flat
        from raft_tpu import parallel

        n, d, m, k = 2048, 16, 40, 8
        x = rng.random((n, d)).astype(np.float32)
        q = rng.random((m, d)).astype(np.float32)
        index = ivf_flat.build(ivf_flat.IndexParams(n_lists=32, seed=0), x)

        # probing every local list on every shard == exhaustive search
        params = ivf_flat.SearchParams(n_probes=32)
        dists, ids = parallel.ivf.search(comms, params, index, q, k)
        dists, ids = np.asarray(dists), np.asarray(ids)

        d2 = ((q[:, None, :].astype(np.float64) - x[None]) ** 2).sum(-1)
        want = np.sort(d2, 1)[:, :k]
        np.testing.assert_allclose(np.sort(dists, 1), want, atol=1e-3, rtol=1e-3)
        # ids are global dataset rows
        gathered = ((q.astype(np.float64) - x[ids[:, 0]]) ** 2).sum(-1)
        np.testing.assert_allclose(gathered, want[:, 0], atol=1e-3, rtol=1e-3)

    def test_partial_probe_recall(self, comms, rng):
        from raft_tpu.neighbors import ivf_flat
        from raft_tpu import parallel

        n, d, m, k = 4096, 12, 50, 5
        x = rng.random((n, d)).astype(np.float32)
        q = rng.random((m, d)).astype(np.float32)
        index = ivf_flat.build(ivf_flat.IndexParams(n_lists=64, seed=1), x)
        dists, ids = parallel.ivf.search(
            comms, ivf_flat.SearchParams(n_probes=4), index, q, k
        )
        ids = np.asarray(ids)
        d2 = ((q[:, None, :].astype(np.float64) - x[None]) ** 2).sum(-1)
        want_i = np.argsort(d2, 1)[:, :k]
        recall = np.mean([len(set(ids[i]) & set(want_i[i])) / k for i in range(m)])
        # 4 probes/shard x 8 shards = 32 of 64 lists scanned
        assert recall > 0.8, recall

    def test_non_divisible_lists_padded(self, comms, rng):
        """n_lists not divisible by the mesh (sub-list splitting makes it
        data-dependent) → empty padding lists, results unaffected."""
        from raft_tpu.neighbors import ivf_flat
        from raft_tpu import parallel

        n, d, m, k = 1024, 8, 20, 4
        x = rng.random((n, d)).astype(np.float32)
        q = rng.random((m, d)).astype(np.float32)
        index = ivf_flat.build(ivf_flat.IndexParams(n_lists=20, seed=0), x)  # 20 % 8 != 0
        dists, ids = parallel.ivf.search(
            comms, ivf_flat.SearchParams(n_probes=3), index, q, k
        )
        ids = np.asarray(ids)
        assert ids.shape == (m, k) and (ids >= 0).all()
        d2 = ((q[:, None, :].astype(np.float64) - x[None]) ** 2).sum(-1)
        want_i = np.argsort(d2, 1)[:, :k]
        recall = np.mean([len(set(ids[i]) & set(want_i[i])) / k for i in range(m)])
        assert recall > 0.8, recall


class TestDistributedIvfPq:
    def test_matches_single_device_recall(self, comms, rng):
        from raft_tpu.neighbors import ivf_pq

        x = rng.random((1024, 16)).astype(np.float32)
        q = rng.random((20, 16)).astype(np.float32)
        idx = ivf_pq.build(ivf_pq.IndexParams(n_lists=16, pq_dim=8, seed=0), x)
        sp = ivf_pq.SearchParams(n_probes=2)
        d_one, i_one = ivf_pq.search(sp, idx, q, 5)
        d_dist, i_dist = parallel.ivf.search_pq(comms, sp, idx, q, 5)
        assert np.asarray(d_dist).shape == (20, 5)
        # per-shard probing covers >= the single-chip probe set, so recall vs
        # exact can only improve; require parity with single-device results
        full = sp_dist.cdist(q, x, "sqeuclidean")
        gt = np.argsort(full, axis=1)[:, :5]
        def recall(ids):
            ids = np.asarray(ids)
            return np.mean([len(set(ids[r]) & set(gt[r])) / 5 for r in range(20)])
        assert recall(i_dist) >= recall(i_one) - 1e-9

    def test_pads_non_divisible_lists(self, comms, rng):
        from raft_tpu.neighbors import ivf_pq

        x = rng.random((600, 8)).astype(np.float32)
        idx = ivf_pq.build(ivf_pq.IndexParams(n_lists=13, pq_dim=4, seed=0), x)
        d, i = parallel.ivf.search_pq(
            comms, ivf_pq.SearchParams(n_probes=1), idx, x[:7], 3)
        assert np.asarray(i).shape == (7, 3)
        assert (np.asarray(i) >= 0).all()

    def test_pq8_split_index_shards(self, comms, rng):
        """Nibble-split pq8 indexes carry the extra list_consts array; the
        distributed search must shard it alongside the lists and match the
        single-device results."""
        from raft_tpu.neighbors import ivf_pq

        x = rng.random((1024, 16)).astype(np.float32)
        q = rng.random((20, 16)).astype(np.float32)
        idx = ivf_pq.build(
            ivf_pq.IndexParams(n_lists=16, pq_dim=8, pq_bits=8, seed=0), x)
        assert idx.pq_split
        sp = ivf_pq.SearchParams(n_probes=idx.n_lists)
        d_one, i_one = ivf_pq.search(sp, idx, q, 5)
        d_dist, i_dist = parallel.ivf.search_pq(comms, sp, idx, q, 5)
        # full probe coverage on both sides -> identical (consts-dependent)
        # score profiles; distance-level rather than id-level equality, since
        # equal-code ties at the k boundary may legitimately resolve to
        # different ids between the two select paths
        np.testing.assert_allclose(np.sort(np.asarray(d_one), axis=1),
                                   np.sort(np.asarray(d_dist), axis=1),
                                   rtol=1e-5)


class TestDistributedIvfBuild:
    """Distributed index BUILD (VERDICT r4 #3): no chip ever holds the full
    dataset — coarse centers via psum-EM, shard-local encode, list-block
    psum fill. Exhaustive probing of the built index is EXACT for L2, so
    parity is vs the f64 ground truth (the dryrun asserts the same)."""

    def test_flat_build_exhaustive_exact(self, comms, rng):
        from raft_tpu.neighbors import ivf_flat
        from raft_tpu import parallel

        n, d, m, k = 2048, 16, 40, 8
        x = rng.random((n, d)).astype(np.float32)
        q = rng.random((m, d)).astype(np.float32)
        idx = parallel.ivf.build(
            comms, ivf_flat.IndexParams(n_lists=32, seed=0), x)
        assert idx.n_lists == 32
        assert int(np.asarray(idx.list_sizes).sum()) == n
        # every dataset row present exactly once
        ids_stored = np.asarray(idx.list_ids)
        assert sorted(ids_stored[ids_stored >= 0].tolist()) == list(range(n))
        # distributed search of the sharded index (no gather: lists divide)
        dists, ids = parallel.ivf.search(
            comms, ivf_flat.SearchParams(n_probes=32 // comms.size()),
            idx, q, k)
        d2 = ((q[:, None, :].astype(np.float64) - x[None]) ** 2).sum(-1)
        want = np.sort(d2, 1)[:, :k]
        np.testing.assert_allclose(np.sort(np.asarray(dists), 1), want,
                                   atol=1e-3, rtol=1e-3)
        # and the single-chip search consumes the same index directly
        d1, i1 = ivf_flat.search(ivf_flat.SearchParams(n_probes=32), idx, q, k)
        np.testing.assert_allclose(np.sort(np.asarray(d1), 1), want,
                                   atol=1e-3, rtol=1e-3)

    def test_flat_extend(self, comms, rng):
        from raft_tpu.neighbors import ivf_flat
        from raft_tpu import parallel

        n, d = 1024, 8
        x = rng.random((2 * n, d)).astype(np.float32)
        q = x[:16]
        idx = parallel.ivf.build(
            comms, ivf_flat.IndexParams(n_lists=16, seed=0), x[:n])
        idx2 = parallel.ivf.extend(comms, idx, x[n:])
        assert int(np.asarray(idx2.list_sizes).sum()) == 2 * n
        ids_stored = np.asarray(idx2.list_ids)
        assert sorted(ids_stored[ids_stored >= 0].tolist()) == list(range(2 * n))
        dists, ids = parallel.ivf.search(
            comms, ivf_flat.SearchParams(n_probes=2), idx2, q, 4)
        d2 = ((q[:, None, :].astype(np.float64) - x[None]) ** 2).sum(-1)
        want = np.sort(d2, 1)[:, :4]
        np.testing.assert_allclose(np.sort(np.asarray(dists), 1), want,
                                   atol=1e-3, rtol=1e-3)

    def test_flat_build_uint8(self, comms, rng):
        from raft_tpu.neighbors import ivf_flat
        from raft_tpu import parallel

        n, d = 1024, 16
        x = rng.integers(0, 256, (n, d), dtype=np.uint8)
        q = x[:20]
        idx = parallel.ivf.build(
            comms, ivf_flat.IndexParams(n_lists=16, seed=0), x)
        assert idx.data_kind == "uint8"
        dists, ids = parallel.ivf.search(
            comms, ivf_flat.SearchParams(n_probes=2), idx, q, 4)
        d2 = ((q[:, None, :].astype(np.float64)
               - x[None].astype(np.float64)) ** 2).sum(-1)
        want = np.sort(d2, 1)[:, :4]
        np.testing.assert_allclose(np.sort(np.asarray(dists), 1), want,
                                   atol=1e-3, rtol=1e-3)

    def test_flat_build_minibatch_exhaustive_exact(self, comms, rng):
        """Distributed mini-batch psum-EM (ISSUE 6): the numeric-parity
        dryrun bar is unchanged — exhaustive probing of the minibatch-built
        index is EXACT vs the f64 ground truth, and every row is stored
        exactly once. The EM loop only moves CENTERS; the closing full
        passes (sharpening + list fill) are identical machinery to full EM,
        so build correctness cannot depend on the mode."""
        from raft_tpu.neighbors import ivf_flat
        from raft_tpu import parallel

        n, d, m, k = 2048, 16, 40, 8
        x = rng.random((n, d)).astype(np.float32)
        q = rng.random((m, d)).astype(np.float32)
        idx = parallel.ivf.build(
            comms, ivf_flat.IndexParams(n_lists=32, seed=0,
                                        kmeans_train_mode="minibatch",
                                        kmeans_batch_rows=512), x)
        assert int(np.asarray(idx.list_sizes).sum()) == n
        ids_stored = np.asarray(idx.list_ids)
        assert sorted(ids_stored[ids_stored >= 0].tolist()) == list(range(n))
        dists, ids = parallel.ivf.search(
            comms, ivf_flat.SearchParams(n_probes=32 // comms.size()),
            idx, q, k)
        d2 = ((q[:, None, :].astype(np.float64) - x[None]) ** 2).sum(-1)
        want = np.sort(d2, 1)[:, :k]
        np.testing.assert_allclose(np.sort(np.asarray(dists), 1), want,
                                   atol=1e-3, rtol=1e-3)

    def test_pq_build_minibatch_recall_parity(self, comms, rng):
        """Distributed mini-batch build recall at parity with the
        single-chip mini-batch build of the same config on the same data
        (the same bar as test_pq_build_recall for full EM)."""
        from raft_tpu.neighbors import ivf_pq
        from raft_tpu import parallel

        centers = rng.random((16, 16)).astype(np.float32) * 10
        lab = rng.integers(0, 16, 2048)
        x = (centers[lab] + 0.3 * rng.standard_normal((2048, 16))).astype(np.float32)
        q = x[:32]
        params = ivf_pq.IndexParams(n_lists=16, pq_dim=8, pq_bits=4, seed=0,
                                    kmeans_train_mode="minibatch",
                                    kmeans_batch_rows=512)
        idx = parallel.ivf.build_pq(comms, params, x)
        assert int(np.asarray(idx.list_sizes).sum()) == 2048
        full = sp_dist.cdist(q, x, "sqeuclidean")
        gt = np.argsort(full, axis=1)[:, :5]

        def rec(ids):
            ids = np.asarray(ids)
            return np.mean([len(set(ids[r]) & set(gt[r])) / 5 for r in range(32)])

        _, i_dist = parallel.ivf.search_pq(
            comms, ivf_pq.SearchParams(n_probes=2), idx, q, 5)
        one = ivf_pq.build(params, x)
        _, i_ref = ivf_pq.search(ivf_pq.SearchParams(n_probes=16), one, q, 5)
        assert rec(i_dist) > rec(i_ref) - 0.1, (rec(i_dist), rec(i_ref))

    def test_minibatch_distributed_kmeans(self, comms, rng):
        """parallel.kmeans.fit honors KMeansParams.train_mode: mini-batch
        Lloyd converges to a comparable partition (inertia within 10% of
        full EM) on blob data."""
        from raft_tpu.cluster import kmeans

        centers = rng.random((4, 8)).astype(np.float32) * 8
        lab = rng.integers(0, 4, 1024)
        x = (centers[lab] + 0.2 * rng.standard_normal((1024, 8))).astype(np.float32)
        full = parallel.kmeans.fit(
            comms, KMeansParams(n_clusters=4, seed=0, max_iter=30), x)
        mb = parallel.kmeans.fit(
            comms, KMeansParams(n_clusters=4, seed=0, max_iter=30,
                                train_mode="minibatch", batch_rows=256), x)
        assert float(mb.inertia) < 1.10 * float(full.inertia), (
            float(mb.inertia), float(full.inertia))

    def test_pq_build_recall(self, comms, rng):
        from raft_tpu.neighbors import ivf_pq
        from raft_tpu import parallel

        # clustered data so PQ has signal; pq4 (16 codes) per_subspace
        centers = rng.random((16, 16)).astype(np.float32) * 10
        lab = rng.integers(0, 16, 2048)
        x = (centers[lab] + 0.3 * rng.standard_normal((2048, 16))).astype(np.float32)
        q = x[:32]
        idx = parallel.ivf.build_pq(
            comms, ivf_pq.IndexParams(n_lists=16, pq_dim=8, pq_bits=4, seed=0), x)
        assert int(np.asarray(idx.list_sizes).sum()) == 2048
        full = sp_dist.cdist(q, x, "sqeuclidean")
        gt = np.argsort(full, axis=1)[:, :5]

        def rec(ids):
            ids = np.asarray(ids)
            return np.mean([len(set(ids[r]) & set(gt[r])) / 5 for r in range(32)])

        # raw PQ recall must be at parity with a single-chip build of the
        # same config on the same data (pq4 on this config is inherently
        # coarse — the bar is the build, not the quantizer)
        d_dist, i_dist = parallel.ivf.search_pq(
            comms, ivf_pq.SearchParams(n_probes=2), idx, q, 5)
        one = ivf_pq.build(
            ivf_pq.IndexParams(n_lists=16, pq_dim=8, pq_bits=4, seed=0), x)
        _, i_ref = ivf_pq.search(ivf_pq.SearchParams(n_probes=16), one, q, 5)
        assert rec(i_dist) > rec(i_ref) - 0.1, (rec(i_dist), rec(i_ref))
        # and the standard refine pass tracks the single-chip build's
        # refined operating point (absolute recall here is set by pq4's
        # coarseness on this deliberately hard config, not by the build)
        from raft_tpu.neighbors.refine import refine

        _, cand = parallel.ivf.search_pq(
            comms, ivf_pq.SearchParams(n_probes=2), idx, q, 20)
        _, i_rf = refine(x, q, cand, 5)
        _, cand1 = ivf_pq.search(ivf_pq.SearchParams(n_probes=16), one, q, 20)
        _, i_rf1 = refine(x, q, cand1, 5)
        assert rec(i_rf) > rec(i_rf1) - 0.1, (rec(i_rf), rec(i_rf1))
        assert rec(i_rf) > 0.6, rec(i_rf)
        # single-chip search consumes the sharded-built index too
        _, i_one = ivf_pq.search(ivf_pq.SearchParams(n_probes=16), idx, q, 5)
        assert rec(i_one) > rec(i_ref) - 0.1, (rec(i_one), rec(i_ref))

    def test_pq_build_byte(self, comms, rng):
        """Sharded byte-dataset ingestion (this PR's end-to-end axis): the
        distributed build must ingest int8/uint8 identically to the
        single-chip build — shift into the s8 domain, train/encode on the
        exact f32 image — and carry data_kind so search coerces queries."""
        from raft_tpu.neighbors import ivf_pq
        from raft_tpu import parallel

        centers = rng.integers(60, 196, (16, 16))
        lab = rng.integers(0, 16, 2048)
        x = np.clip(centers[lab] + rng.normal(0, 10, (2048, 16)),
                    0, 255).astype(np.uint8)
        q = x[:32]
        idx = parallel.ivf.build_pq(
            comms, ivf_pq.IndexParams(n_lists=16, pq_dim=8, seed=0), x)
        assert idx.data_kind == "uint8"
        assert int(np.asarray(idx.list_sizes).sum()) == 2048
        _, ids = parallel.ivf.search_pq(
            comms, ivf_pq.SearchParams(n_probes=16), idx, q, 10)
        d2 = ((q[:, None, :].astype(np.float64)
               - x[None].astype(np.float64)) ** 2).sum(-1)
        gt = np.argsort(d2, axis=1)[:, :10]

        def rec(i):
            i = np.asarray(i)
            return np.mean([len(set(i[r]) & set(gt[r])) / 10
                            for r in range(32)])

        # the bar is build parity, not the quantizer (pq4 is coarse on
        # this config — same contract as test_pq_build_recall): the sharded
        # byte build must track a single-chip build of the same config
        one = ivf_pq.build(ivf_pq.IndexParams(n_lists=16, pq_dim=8, seed=0), x)
        assert one.data_kind == "uint8"
        _, i_ref = ivf_pq.search(ivf_pq.SearchParams(n_probes=16), one, q, 10)
        assert rec(ids) > rec(i_ref) - 0.1, (rec(ids), rec(i_ref))
        assert rec(ids) > 0.5, rec(ids)
        # the single-chip search consumes the sharded byte index too
        _, i_one = ivf_pq.search(ivf_pq.SearchParams(n_probes=16), idx, q, 10)
        assert rec(i_one) > rec(i_ref) - 0.1, (rec(i_one), rec(i_ref))

    def test_pq8_split_build(self, comms, rng):
        from raft_tpu.neighbors import ivf_pq
        from raft_tpu import parallel

        x = rng.random((1024, 16)).astype(np.float32)
        idx = parallel.ivf.build_pq(
            comms, ivf_pq.IndexParams(n_lists=16, pq_dim=8, pq_bits=8, seed=0), x)
        assert idx.pq_split
        # L2 split indexes must carry per-vector cross-term consts
        assert idx.list_consts.shape == idx.list_ids.shape
        d, i = parallel.ivf.search_pq(
            comms, ivf_pq.SearchParams(n_probes=2), idx, x[:8], 3)
        i = np.asarray(i)
        # self-search: the query itself must be found at the top
        assert (i[:, 0] == np.arange(8)).mean() > 0.7

    def test_build_guards(self, comms, rng):
        from raft_tpu.core import RaftError
        from raft_tpu.neighbors import ivf_flat
        from raft_tpu import parallel

        with pytest.raises(RaftError, match="divide the mesh axis"):
            parallel.ivf.build(
                comms, ivf_flat.IndexParams(n_lists=16, seed=0),
                rng.random((1001, 8)).astype(np.float32))
        with pytest.raises(RaftError, match="n_lists"):
            parallel.ivf.build(
                comms, ivf_flat.IndexParams(n_lists=20, seed=0),
                rng.random((1024, 8)).astype(np.float32))


class TestDistributedCagra:
    def test_matches_exact(self, comms, rng):
        from raft_tpu.parallel import cagra as pcagra
        from raft_tpu.neighbors import cagra

        x = rng.random((512, 16)).astype(np.float32)
        q = rng.random((16, 16)).astype(np.float32)
        params = cagra.IndexParams(graph_degree=8, intermediate_graph_degree=16,
                                   build_n_lists=4, build_n_probes=4)
        sharded = pcagra.build(comms, params, x)
        assert sharded.n_shards == 8 and sharded.rows_per_shard == 64
        d, i = pcagra.search(comms, cagra.SearchParams(itopk_size=16), sharded, q, k=5)
        d, i = np.asarray(d), np.asarray(i)
        full = sp_dist.cdist(q, x, "sqeuclidean")
        gt = np.argsort(full, axis=1)[:, :5]
        rec = np.mean([len(set(i[r]) & set(gt[r])) / 5 for r in range(16)])
        # 64-row shards searched with itopk=16 are near-exhaustive
        assert rec > 0.95, rec
        # global ids must be consistent with reported distances
        got_d = np.take_along_axis(full, i, 1)
        np.testing.assert_allclose(got_d, d, rtol=1e-3, atol=1e-3)


class TestDocumentedEdgeSemantics:
    """Pin the comms veneer's documented TPU trade-offs (comms.py inline
    docs): reduce() ignores root (value lands everywhere), gather() returns
    full copies on every shard, PROD handles zeros/signs exactly, alltoall
    requires divisibility. The reference's rooted semantics are a host-side
    concern on ICI; these tests make the divergence explicit."""

    def test_reduce_lands_on_all_ranks(self, comms):
        def f(x):
            return comms.reduce(x, root=2, op="sum")
        x = np.arange(8, dtype=np.float32).reshape(8, 1)
        out = comms.shard_map(f, in_specs=P("data"), out_specs=P("data"))(x)
        # every shard (not just root=2) holds the full sum
        np.testing.assert_allclose(np.asarray(out), np.full((8, 1), 28.0))

    def test_gather_returns_full_copies_everywhere(self, comms):
        def f(x):
            return comms.gather(x, root=0).reshape(8, 1)  # (8 shards, 1) gathered
        x = np.arange(8, dtype=np.float32).reshape(8, 1)
        out = comms.shard_map(f, in_specs=P("data"), out_specs=P(None, "data"))(x)
        got = np.asarray(out)  # (8, 8): column s is shard s's gathered copy
        assert got.shape == (8, 8)
        for s in range(8):
            np.testing.assert_allclose(got[:, s], np.arange(8, dtype=np.float32))

    def test_prod_with_zero_and_signs(self, comms):
        vals = np.array([2.0, -1.0, 3.0, -2.0, 1.0, 1.0, -1.0, 2.0], np.float32)
        def f(x):
            return comms.allreduce(x, op="prod")
        out = comms.shard_map(f, in_specs=P("data"), out_specs=P("data"))(
            vals.reshape(8, 1))
        np.testing.assert_allclose(np.asarray(out), np.full((8, 1), np.prod(vals)),
                                   rtol=1e-5)
        with_zero = vals.copy(); with_zero[3] = 0.0
        out = comms.shard_map(f, in_specs=P("data"), out_specs=P("data"))(
            with_zero.reshape(8, 1))
        np.testing.assert_allclose(np.asarray(out), np.zeros((8, 1)))

    def test_alltoall_semantics_and_divisibility(self, comms):
        def f(x):
            return comms.alltoall(x)
        x = np.arange(8 * 8, dtype=np.float32).reshape(64, 1)
        out = np.asarray(
            comms.shard_map(f, in_specs=P("data"), out_specs=P("data"))(x))
        # shard i's row j goes to shard j's slot i: a block transpose
        expected = x.reshape(8, 8, 1).transpose(1, 0, 2).reshape(64, 1)
        np.testing.assert_allclose(out, expected)
        # non-divisible per-shard rows (9 per shard, split by 8) must fail
        bad = np.zeros((72, 1), np.float32)
        with pytest.raises(Exception):
            comms.shard_map(f, in_specs=P("data"), out_specs=P("data"))(bad)


class TestFailurePaths:
    """M5 analogue (reference comms/detail/util.hpp:109-136: sync_stream
    polls ncclCommGetAsyncError and surfaces status_t::ABORT). The TPU
    contract, pinned here and documented in docs/using_comms.md:

    - errors raised while TRACING a shard_map body (bad op names, shape
      mismatches) propagate as ordinary Python exceptions at call time;
    - a runtime fault in any shard aborts the whole computation and
      surfaces as an exception no later than ``Comms.sync_stream`` (the
      block_until_ready analogue of the NCCL abort path);
    - a cancelled search raises InterruptedException at its next
      ``synchronize`` cancellation point and leaves the token reusable.
    """

    def test_trace_time_error_propagates(self, comms):
        from raft_tpu.core import RaftError

        def bad(x):
            return comms.allreduce(x, op="nonsense")

        fn = comms.shard_map(bad, in_specs=P("data"), out_specs=P("data"))
        with pytest.raises(RaftError):
            fn(np.ones((8, 4), np.float32))

    def test_runtime_fault_surfaces_at_sync(self, comms):
        """Fault injection: one shard's data trips an in-graph check mid-step
        (checkify — the sanctioned data-dependent fault surface; a raw host
        callback raising inside an SPMD execution is NOT recoverable, it
        aborts the process, which is why the contract routes data-dependent
        failures through checkify). The error must surface by sync time, and
        the comms object must remain usable afterwards (the reference aborts
        the NCCL communicator; XLA tears down just the failed execution)."""
        import jax.numpy as jnp
        from jax.experimental import checkify

        def body(x):
            checkify.check(jnp.all(x < 100.0), "injected shard fault")
            return comms.allreduce(x)

        fn = comms.shard_map(body, in_specs=P("data"), out_specs=P())
        checked = checkify.checkify(fn)

        x = np.ones((8, 4), np.float32)
        x[3] = 1000.0  # only shard 3 faults
        err, out = checked(x)
        comms.sync_stream(out)
        with pytest.raises(Exception, match="injected shard fault"):
            err.throw()
        # the session survives a failed execution: same comms, healthy data
        err, ok = checked(np.ones((8, 4), np.float32))
        err.throw()
        comms.sync_stream(ok)
        np.testing.assert_allclose(np.asarray(ok), np.full(np.asarray(ok).shape, 8.0))
        assert np.asarray(ok).size > 0

    def test_cancelled_search_raises_and_token_resets(self, comms):
        """A long multi-batch search cancelled from a controller thread stops
        at its next synchronize() with InterruptedException (reference:
        interruptible::synchronize as cancellation point, interruptible.hpp:83;
        pylibraft test_z_interruptible.py), and the worker thread's token is
        clean afterwards."""
        import threading

        from raft_tpu.core import InterruptedException, synchronize
        from raft_tpu.core.interruptible import cancel, get_token
        from raft_tpu.neighbors import brute_force

        rng = np.random.default_rng(3)
        x = rng.standard_normal((2000, 32), np.float32)
        qbatches = rng.standard_normal((64, 16, 32), np.float32)
        state = {"done": 0}
        ready = threading.Event()
        go = threading.Event()

        def worker():
            get_token()
            state["tid"] = threading.get_ident()
            ready.set()
            go.wait()
            try:
                for qb in qbatches:
                    d, i = brute_force.knn(x, qb, 5)
                    synchronize(d, i)  # cancellation point between batches
                    state["done"] += 1
                state["result"] = "completed"
            except InterruptedException:
                state["result"] = "cancelled"
                # token cleared on throw: the thread is immediately reusable
                d, i = brute_force.knn(x, qbatches[0], 5)
                synchronize(d, i)
                state["post_cancel_ok"] = True

        t = threading.Thread(target=worker)
        t.start()
        ready.wait()
        cancel(state["tid"])
        go.set()
        t.join(60)
        assert state["result"] == "cancelled"
        assert state.get("post_cancel_ok"), "token must reset after the throw"
        assert state["done"] < len(qbatches)


class TestProgramCacheRelease:
    """The driver program caches key on the live Comms (ISSUE 9 satellite):
    cached programs PIN the mesh they were staged for, so a process that
    churns mesh configs (the sharded serving tier) must be able to evict a
    retired communicator's programs — parallel.release_programs."""

    def test_hit_behavior_preserved(self, comms, rng):
        """Same (comms, config) → the SAME program object: the Round-5
        retrace fix survives the lru_cache → ProgramCache conversion."""
        from raft_tpu.parallel import knn as pknn

        x = rng.random((160, 8)).astype(np.float32)
        q = rng.random((4, 8)).astype(np.float32)
        pknn.knn(comms, x, q, k=3)
        keys = pknn._PROGRAMS.keys_for(comms)
        assert keys, "knn did not populate the program cache"
        key = next(k for k in keys if k[1] == 3)
        f1 = pknn._knn_fn(*key)
        f2 = pknn._knn_fn(*key)
        assert f1 is f2

    def test_release_unpins_retired_comms(self, rng):
        """Leak check: a retired mesh's Comms stays reachable while its
        programs are cached (the leak), and is garbage the moment
        release_programs drops them — no jax-internal reference keeps the
        communicator alive."""
        import gc
        import weakref

        import jax
        from jax.sharding import Mesh

        x = rng.random((64, 8)).astype(np.float32)
        q = rng.random((4, 8)).astype(np.float32)
        mesh = Mesh(np.array(jax.devices()[:2]), ("data",))
        c = Comms(mesh, "data")
        d, i = parallel.knn.knn(c, x, q, k=3)
        c.sync_stream(d, i)
        assert len(parallel.knn._PROGRAMS.keys_for(c)) == 1
        ref = weakref.ref(c)
        del mesh, d, i
        gc.collect()
        assert ref() is not None, "sanity: cache must pin the live comms"
        dropped = parallel.release_programs(c)
        assert dropped == 1
        assert parallel.knn._PROGRAMS.keys_for(c) == []
        del c
        gc.collect()
        assert ref() is None, (
            "retired comms still reachable after release_programs — a "
            "cached program (or a new strong reference) pins the mesh")

    def test_release_is_per_comms_and_bounded(self, comms, rng):
        """release(comms) must not evict OTHER communicators' programs,
        and the cache keeps its LRU bound."""
        import jax
        from jax.sharding import Mesh

        from raft_tpu.parallel import knn as pknn

        x = rng.random((64, 8)).astype(np.float32)
        q = rng.random((4, 8)).astype(np.float32)
        pknn.knn(comms, x, q, k=3)
        other = Comms(Mesh(np.array(jax.devices()[:2]), ("data",)), "data")
        pknn.knn(other, x, q, k=3)
        assert pknn._PROGRAMS.keys_for(comms)
        parallel.release_programs(other)
        assert pknn._PROGRAMS.keys_for(other) == []
        assert pknn._PROGRAMS.keys_for(comms), "wrong comms was evicted"
        assert pknn._PROGRAMS.maxsize == 256
        assert len(pknn._PROGRAMS) <= 256
