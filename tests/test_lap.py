"""Linear assignment tests.

Reference strategy: cpp/test/linalg (SOLVERS_TEST) checks LAP against known
optimal objectives; here scipy.optimize.linear_sum_assignment is the trusted
host reference (SURVEY.md §4) — exact parity on integer costs, objective
parity within n·eps on floats.
"""

import numpy as np
import pytest
from scipy.optimize import linear_sum_assignment

import jax.numpy as jnp

from raft_tpu.solver import lap_solve


class TestLap:
    @pytest.mark.parametrize("n", [4, 16, 64])
    def test_integer_costs_exact(self, rng, n):
        cost = rng.integers(0, 100, (n, n)).astype(np.float32)
        out = lap_solve(jnp.asarray(cost))
        ri, ci = linear_sum_assignment(cost)
        ref_obj = cost[ri, ci].sum()
        ra = np.asarray(out.row_assignment)
        assert sorted(ra.tolist()) == list(range(n))  # a permutation
        assert float(out.objective) == pytest.approx(ref_obj)
        assert cost[np.arange(n), ra].sum() == pytest.approx(ref_obj)

    def test_float_costs_near_optimal(self, rng):
        n = 48
        cost = rng.random((n, n)).astype(np.float32)
        out = lap_solve(jnp.asarray(cost), eps=1e-4)
        ri, ci = linear_sum_assignment(cost)
        ref_obj = cost[ri, ci].sum()
        assert float(out.objective) <= ref_obj + n * 1e-4 + 1e-4

    def test_maximize(self, rng):
        n = 24
        cost = rng.integers(0, 50, (n, n)).astype(np.float32)
        out = lap_solve(jnp.asarray(cost), maximize=True)
        ri, ci = linear_sum_assignment(cost, maximize=True)
        assert float(out.objective) == pytest.approx(cost[ri, ci].sum())

    def test_batched(self, rng):
        b, n = 5, 20
        cost = rng.integers(0, 100, (b, n, n)).astype(np.float32)
        out = lap_solve(jnp.asarray(cost))
        assert out.row_assignment.shape == (b, n)
        for i in range(b):
            ri, ci = linear_sum_assignment(cost[i])
            assert float(out.objective[i]) == pytest.approx(cost[i][ri, ci].sum())

    def test_row_col_assignment_consistent(self, rng):
        n = 32
        cost = rng.integers(0, 100, (n, n)).astype(np.float32)
        out = lap_solve(jnp.asarray(cost))
        ra, ca = np.asarray(out.row_assignment), np.asarray(out.col_assignment)
        for i in range(n):
            assert ca[ra[i]] == i

    def test_duals_feasible(self, rng):
        # complementary slackness (within eps): u_i + v_j <= c_ij + eps
        n = 16
        cost = rng.integers(0, 100, (n, n)).astype(np.float32)
        out = lap_solve(jnp.asarray(cost))
        u = np.asarray(out.row_duals)[:, None]
        v = np.asarray(out.col_duals)[None, :]
        eps = 1.0 / (n + 1)
        assert np.all(u + v <= cost + eps + 1e-5)
