"""Tests for the fused distance+top-k Pallas kernel (ops/fused_knn.py).

Runs in interpret mode on the CPU test platform; on TPU the same code paths
compile to Mosaic. Ground truth is the XLA GEMM + lax.top_k path (_bf_knn),
mirroring the reference's select_k tests that compare against a full sort
(cpp/test/matrix/select_k.cu).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from raft_tpu.distance.types import DistanceType
from raft_tpu.neighbors.brute_force import _bf_knn, knn
from raft_tpu.ops.fused_knn import fused_knn

N, D, M, K = 4500, 72, 300, 10  # n >= 4096, d >= 64: knn() dispatches to the kernel


def assert_knn_equiv(dv, di, rd, ri, rtol=1e-5, atol=1e-6):
    """Positionwise distances must match; ids may differ only on ULP ties.

    The fused kernel and the XLA pipeline accumulate dot products in different
    orders, so two neighbors whose distances differ below f32 reassociation
    noise may swap positions (documented in ops/fused_knn.py).
    """
    dv, di, rd, ri = map(np.asarray, (dv, di, rd, ri))
    np.testing.assert_allclose(dv, rd, rtol=rtol, atol=atol)
    mism = di != ri
    if mism.any():
        # every mismatched slot must be a near-tie: the two orderings report
        # the same distance there (already enforced by allclose above), and
        # the swapped ids must appear in each other's rows
        rows = np.unique(np.where(mism)[0])
        for r in rows:
            assert set(di[r]) == set(ri[r]) or np.allclose(
                np.sort(dv[r]), np.sort(rd[r]), rtol=rtol, atol=atol), r



@pytest.fixture(autouse=True)
def _enable_dispatch(monkeypatch):
    # knn() only dispatches to the kernel on TPU; tests opt in to interpret mode
    monkeypatch.setenv("RAFT_TPU_FUSED_KNN_INTERPRET", "1")


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(7)
    x = rng.random((N, D), np.float32)
    q = rng.random((M, D), np.float32)
    return jnp.asarray(x), jnp.asarray(q)


def test_l2_exact_matches_xla(data):
    x, q = data
    dv, di = fused_knn(x, q, K, metric="l2", interpret=True)
    rd, ri = _bf_knn(x, q, K, DistanceType.L2Expanded, 2.0, 300, 300)
    assert_knn_equiv(dv, di, rd, ri)


def test_l2_sqrt(data):
    x, q = data
    dv, di = fused_knn(x, q, K, metric="l2", sqrt=True, interpret=True)
    rd, ri = _bf_knn(x, q, K, DistanceType.L2SqrtExpanded, 2.0, 300, 300)
    assert_knn_equiv(dv, di, rd, ri)


def test_inner_product(data):
    x, q = data
    dv, di = fused_knn(x, q, K, metric="ip", interpret=True)
    rd, ri = _bf_knn(x, q, K, DistanceType.InnerProduct, 2.0, 300, 300)
    assert_knn_equiv(dv, di, rd, ri)


def test_knn_dispatch_cosine(data):
    x, q = data
    # public knn() routes to the fused kernel (n >= 4096, CPU -> interpret)
    dv, di = knn(x, q, K, metric="cosine")
    rd, ri = _bf_knn(x, q, K, DistanceType.CosineExpanded, 2.0, 300, 300)
    # cosine goes through a normalize-then-ip rewrite; neighbor sets must
    # match except where 1-ULP normalization differences reorder near-ties
    di, ri = np.asarray(di), np.asarray(ri)
    overlap = np.mean([len(set(di[r]) & set(ri[r])) / K for r in range(M)])
    assert overlap > 0.999
    np.testing.assert_allclose(np.sort(np.asarray(dv)), np.sort(np.asarray(rd)),
                               rtol=1e-4, atol=1e-5)


def test_knn_dispatch_l2_exact(data):
    x, q = data
    dv, di = knn(x, q, K)  # sqeuclidean default
    rd, ri = _bf_knn(x, q, K, DistanceType.L2Expanded, 2.0, 300, 300)
    assert_knn_equiv(dv, di, rd, ri)


def test_k_edges(data):
    x, q = data
    for k in (1, 64):
        dv, di = fused_knn(x, q, k, metric="l2", interpret=True)
        rd, ri = _bf_knn(x, q, k, DistanceType.L2Expanded, 2.0, 300, 300)
        assert_knn_equiv(dv, di, rd, ri)


def test_keep_mask(data):
    x, q = data
    rng = np.random.default_rng(3)
    keep = rng.random(N) < 0.5
    dv, di = fused_knn(x, q, K, metric="l2", keep_mask=jnp.asarray(keep),
                       interpret=True)
    rd, ri = _bf_knn(x, q, K, DistanceType.L2Expanded, 2.0, 300, 300,
                     jnp.asarray(keep))
    assert_knn_equiv(dv, di, rd, ri)


def test_keep_mask_fewer_than_k():
    rng = np.random.default_rng(5)
    x = jnp.asarray(rng.random((4200, 8), np.float32))
    q = jnp.asarray(rng.random((10, 8), np.float32))
    keep = np.zeros(4200, bool)
    keep[:4] = True                   # only 4 admissible rows, k=10
    dv, di = fused_knn(x, q, 10, metric="l2", keep_mask=jnp.asarray(keep),
                       interpret=True)
    dv, di = np.asarray(dv), np.asarray(di)
    assert (di[:, 4:] == -1).all()
    assert np.isinf(dv[:, 4:]).all()
    assert set(di[0, :4]) == {0, 1, 2, 3}


class TestInt8:
    """int8/uint8 ingestion (VERDICT r4 #2; reference: the int8_t/uint8_t
    brute-force instantiations). At d=72 every intermediate is an integer
    below f32's exact range, so the s8 kernel must match the f32 pipeline
    BITWISE, not just to tolerance."""

    @pytest.fixture(scope="class")
    def idata(self):
        rng = np.random.default_rng(11)
        xu = rng.integers(0, 256, (N, D), dtype=np.uint8)
        qu = rng.integers(0, 256, (M, D), dtype=np.uint8)
        return xu, qu

    @pytest.mark.parametrize("dt", [np.int8, np.uint8])
    def test_l2_exact_vs_f32(self, idata, dt):
        xu, qu = idata
        x = xu.astype(dt) if dt == np.uint8 else (
            xu.astype(np.int16) - 128).astype(np.int8)
        q = qu.astype(dt) if dt == np.uint8 else (
            qu.astype(np.int16) - 128).astype(np.int8)
        dv, di = knn(jnp.asarray(x), jnp.asarray(q), K)  # s8 dispatch
        rd, ri = _bf_knn(jnp.asarray(x.astype(np.float32)),
                         jnp.asarray(q.astype(np.float32)),
                         K, DistanceType.L2Expanded, 2.0, 300, 300)
        assert_knn_equiv(dv, di, rd, ri, rtol=0, atol=0)

    @pytest.mark.parametrize("dt", [np.int8, np.uint8])
    def test_inner_product_exact(self, idata, dt):
        xu, qu = idata
        x = xu.astype(dt) if dt == np.uint8 else (
            xu.astype(np.int16) - 128).astype(np.int8)
        q = qu.astype(dt) if dt == np.uint8 else (
            qu.astype(np.int16) - 128).astype(np.int8)
        dv, di = knn(jnp.asarray(x), jnp.asarray(q), K, metric="inner_product")
        rd, ri = _bf_knn(jnp.asarray(x.astype(np.float32)),
                         jnp.asarray(q.astype(np.float32)),
                         K, DistanceType.InnerProduct, 2.0, 300, 300)
        assert_knn_equiv(dv, di, rd, ri, rtol=0, atol=0)

    def test_uint8_keep_mask(self, idata):
        xu, qu = idata
        rng = np.random.default_rng(13)
        keep = rng.random(N) < 0.5
        dv, di = knn(jnp.asarray(xu), jnp.asarray(qu), K,
                     sample_filter=jnp.asarray(keep))
        rd, ri = _bf_knn(jnp.asarray(xu.astype(np.float32)),
                         jnp.asarray(qu.astype(np.float32)),
                         K, DistanceType.L2Expanded, 2.0, 300, 300,
                         jnp.asarray(keep))
        assert_knn_equiv(dv, di, rd, ri, rtol=0, atol=0)

    def test_mixed_dtype_rejected(self, idata):
        from raft_tpu.core import RaftError

        xu, qu = idata
        with pytest.raises(RaftError, match="share a dtype"):
            knn(jnp.asarray(xu), jnp.asarray(
                (qu.astype(np.int16) - 128).astype(np.int8)), K)

    def test_small_shape_falls_back_to_f32(self, idata):
        """Below the kernel's shape gate the integer path casts to f32 —
        still exact for 8-bit values."""
        xu, qu = idata
        dv, di = knn(jnp.asarray(xu[:1000]), jnp.asarray(qu[:20]), K)
        rd, ri = _bf_knn(jnp.asarray(xu[:1000].astype(np.float32)),
                         jnp.asarray(qu[:20].astype(np.float32)),
                         K, DistanceType.L2Expanded, 2.0, 300, 300)
        assert_knn_equiv(dv, di, rd, ri, rtol=0, atol=0)


def test_compute_modes_recall(data):
    x, q = data
    rd, ri = _bf_knn(x, q, K, DistanceType.L2Expanded, 2.0, 300, 300)
    ri = np.asarray(ri)
    for mode in ("f32x3", "bf16"):
        dv, di = fused_knn(x, q, K, metric="l2", mode=mode, interpret=True)
        di = np.asarray(di)
        overlap = np.mean([len(set(di[r]) & set(ri[r])) / K for r in range(M)])
        assert overlap > (0.999 if mode == "f32x3" else 0.95), (mode, overlap)
