"""Pairwise distance tests vs scipy/numpy references.

Analogue of the reference's distance gtest fixture
(cpp/test/distance/distance_base.cuh, instantiated by 19 dist_*.cu files) and
pylibraft's test_distance.py: every metric is checked against an independent
host implementation on small random data.
"""

import numpy as np
import pytest
from scipy.spatial import distance as sp_dist
from scipy.special import rel_entr

from raft_tpu.core import RaftError
from raft_tpu.distance import DistanceType, fused_l2_nn, fused_l2_nn_argmin, pairwise_distance

ATOL = 1e-4
RTOL = 1e-4


def _data(rng, m=33, n=47, d=19, positive=False, binary=False):
    x = rng.random((m, d)).astype(np.float32)
    y = rng.random((n, d)).astype(np.float32)
    if positive:
        x += 0.1
        y += 0.1
        # probability-vector normalization for divergence metrics
        x /= x.sum(1, keepdims=True)
        y /= y.sum(1, keepdims=True)
    if binary:
        x = (x > 0.5).astype(np.float32)
        y = (y > 0.5).astype(np.float32)
    return x, y


SCIPY_METRICS = [
    ("euclidean", "euclidean", {}),
    ("l2", "euclidean", {}),
    ("sqeuclidean", "sqeuclidean", {}),
    ("l1", "cityblock", {}),
    ("cityblock", "cityblock", {}),
    ("chebyshev", "chebyshev", {}),
    ("canberra", "canberra", {}),
    ("braycurtis", "braycurtis", {}),
    ("correlation", "correlation", {}),
    ("cosine", "cosine", {}),
    ("minkowski", "minkowski", {"p": 3.0}),
    ("hamming", "hamming", {}),
    ("jensenshannon", "jensenshannon", {}),
]


@pytest.mark.parametrize("ours,scipys,kw", SCIPY_METRICS, ids=[m[0] for m in SCIPY_METRICS])
def test_vs_scipy(rng, ours, scipys, kw):
    positive = ours == "jensenshannon"
    x, y = _data(rng, positive=positive)
    got = np.asarray(pairwise_distance(x, y, metric=ours, metric_arg=kw.get("p", 2.0)))
    want = sp_dist.cdist(x.astype(np.float64), y.astype(np.float64), scipys, **kw)
    np.testing.assert_allclose(got, want, atol=ATOL, rtol=RTOL)


@pytest.mark.parametrize("metric", ["jaccard", "dice", "russellrao"])
def test_binary_metrics(rng, metric):
    x, y = _data(rng, binary=True)
    got = np.asarray(pairwise_distance(x, y, metric=metric))
    want = sp_dist.cdist(x.astype(bool), y.astype(bool), metric)
    np.testing.assert_allclose(got, want, atol=ATOL, rtol=RTOL)


def test_inner_product(rng):
    x, y = _data(rng)
    got = np.asarray(pairwise_distance(x, y, metric="inner_product"))
    np.testing.assert_allclose(got, x @ y.T, atol=ATOL, rtol=RTOL)


def test_kl_divergence(rng):
    # reference semantics: 0.5 * sum(x log(x/y)) (distance_ops/kl_divergence.cuh)
    x, y = _data(rng, positive=True)
    got = np.asarray(pairwise_distance(x, y, metric="kl_divergence"))
    want = 0.5 * rel_entr(x[:, None, :], y[None, :, :]).sum(-1)
    np.testing.assert_allclose(got, want, atol=ATOL, rtol=RTOL)


def test_hellinger(rng):
    x, y = _data(rng, positive=True)
    got = np.asarray(pairwise_distance(x, y, metric="hellinger"))
    want = np.sqrt(np.maximum(1.0 - np.sqrt(x[:, None] * y[None]).sum(-1), 0.0))
    np.testing.assert_allclose(got, want, atol=ATOL, rtol=RTOL)


def test_haversine(rng):
    x = (rng.random((20, 2)).astype(np.float32) - 0.5) * np.array([np.pi, 2 * np.pi])
    y = (rng.random((15, 2)).astype(np.float32) - 0.5) * np.array([np.pi, 2 * np.pi])
    got = np.asarray(pairwise_distance(x.astype(np.float32), y.astype(np.float32), "haversine"))
    lat1, lon1 = x[:, None, 0], x[:, None, 1]
    lat2, lon2 = y[None, :, 0], y[None, :, 1]
    h = np.sin((lat2 - lat1) / 2) ** 2 + np.cos(lat1) * np.cos(lat2) * np.sin((lon2 - lon1) / 2) ** 2
    want = 2 * np.arcsin(np.sqrt(h))
    np.testing.assert_allclose(got, want, atol=1e-4, rtol=1e-4)


def test_self_distance_zero(rng):
    x, _ = _data(rng)
    d = np.asarray(pairwise_distance(x, metric="euclidean"))
    np.testing.assert_allclose(np.diag(d), 0.0, atol=1e-3)
    assert (d >= 0).all()


def test_enum_metric(rng):
    x, y = _data(rng)
    a = np.asarray(pairwise_distance(x, y, DistanceType.L2SqrtExpanded))
    b = sp_dist.cdist(x, y, "euclidean")
    np.testing.assert_allclose(a, b, atol=ATOL, rtol=RTOL)


def test_expanded_vs_unexpanded(rng):
    x, y = _data(rng)
    a = np.asarray(pairwise_distance(x, y, DistanceType.L2Expanded))
    b = np.asarray(pairwise_distance(x, y, DistanceType.L2Unexpanded))
    np.testing.assert_allclose(a, b, atol=1e-3, rtol=1e-3)


def test_tiling_consistency(rng):
    """Tiny workspace forces multi-tile execution; result must be identical."""
    from raft_tpu.core import Resources

    x, y = _data(rng, m=100, n=64, d=16)
    small = Resources(workspace_bytes=64 * 64 * 4 * 20)
    a = np.asarray(pairwise_distance(x, y, "l1", res=small))
    want = sp_dist.cdist(x, y, "cityblock")
    np.testing.assert_allclose(a, want, atol=ATOL, rtol=RTOL)


def test_bad_metric():
    with pytest.raises(RaftError, match="not supported"):
        pairwise_distance(np.zeros((2, 2)), np.zeros((2, 2)), "warp_drive")


def test_shape_mismatch():
    with pytest.raises(RaftError, match="feature dims"):
        pairwise_distance(np.zeros((2, 3)), np.zeros((2, 4)))


def test_haversine_requires_2d():
    with pytest.raises(RaftError, match="haversine"):
        pairwise_distance(np.zeros((2, 3)), np.zeros((2, 3)), "haversine")


class TestFusedL2NN:
    """Analogue of cpp/test/distance/fused_l2_nn.cu."""

    def test_argmin_matches_bruteforce(self, rng):
        x, y = _data(rng, m=200, n=37, d=8)
        dists, idx = fused_l2_nn(x, y)
        full = sp_dist.cdist(x, y, "sqeuclidean")
        np.testing.assert_array_equal(np.asarray(idx), full.argmin(1))
        np.testing.assert_allclose(np.asarray(dists), full.min(1), atol=1e-3, rtol=1e-4)

    def test_sqrt(self, rng):
        x, y = _data(rng, m=50, n=20, d=4)
        dists, _ = fused_l2_nn(x, y, sqrt=True)
        full = sp_dist.cdist(x, y, "euclidean")
        np.testing.assert_allclose(np.asarray(dists), full.min(1), atol=1e-3, rtol=1e-4)

    def test_argmin_only(self, rng):
        x, y = _data(rng, m=64, n=16, d=8)
        idx = fused_l2_nn_argmin(x, y)
        full = sp_dist.cdist(x, y, "sqeuclidean")
        np.testing.assert_array_equal(np.asarray(idx), full.argmin(1))

    def test_tiled(self, rng):
        from raft_tpu.core import Resources

        x, y = _data(rng, m=333, n=100, d=12)
        small = Resources(workspace_bytes=100 * 14 * 4 * 16)
        _, idx = fused_l2_nn(x, y, res=small)
        full = sp_dist.cdist(x, y, "sqeuclidean")
        np.testing.assert_array_equal(np.asarray(idx), full.argmin(1))


def test_fused_l2_nn_large_n_kernel_dispatch(rng, monkeypatch):
    """At n >= 4096 fused_l2_nn routes through the fused Pallas kernel with
    k=1; results must match the XLA path exactly."""
    monkeypatch.setenv("RAFT_TPU_FUSED_KNN_INTERPRET", "1")
    import jax.numpy as jnp
    from raft_tpu.distance import fused_l2_nn
    from raft_tpu.distance.fused_nn import _fused_l2_nn

    x = jnp.asarray(rng.random((200, 80)).astype(np.float32))
    y = jnp.asarray(rng.random((4500, 80)).astype(np.float32))
    d, i = fused_l2_nn(x, y)
    d0, i0 = _fused_l2_nn(x, y, False, 200)
    np.testing.assert_array_equal(np.asarray(i), np.asarray(i0))
    np.testing.assert_allclose(np.asarray(d), np.asarray(d0), rtol=1e-5, atol=1e-5)
