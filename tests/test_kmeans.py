"""k-means tests (reference analogue: cpp/test/cluster/kmeans*.cu,
python/pylibraft/pylibraft/test/test_kmeans.py)."""

import numpy as np
import pytest

from raft_tpu.cluster import KMeansBalancedParams, KMeansParams, kmeans, kmeans_balanced
from raft_tpu.core import RaftError
from raft_tpu.random import make_blobs


@pytest.fixture(scope="module")
def blobs():
    x, labels = make_blobs(1500, 10, n_clusters=5, cluster_std=0.3, seed=7)
    return np.asarray(x), np.asarray(labels)


class TestKMeans:
    def test_fit_recovers_blobs(self, blobs):
        x, true_labels = blobs
        out = kmeans.fit(KMeansParams(n_clusters=5, seed=1), x)
        assert out.centroids.shape == (5, 10)
        # compare partitions via ARI
        from sklearn.metrics import adjusted_rand_score

        ari = adjusted_rand_score(true_labels, np.asarray(out.labels))
        assert ari > 0.95, ari

    def test_inertia_decreases_vs_random_centroids(self, blobs):
        x, _ = blobs
        out = kmeans.fit(KMeansParams(n_clusters=5, seed=0), x)
        rand_cost = float(kmeans.cluster_cost(x, x[:5]))
        assert float(out.inertia) < rand_cost

    def test_predict_matches_fit_labels(self, blobs):
        x, _ = blobs
        out = kmeans.fit(KMeansParams(n_clusters=5, seed=0), x)
        labels, inertia = kmeans.predict(x, out.centroids)
        np.testing.assert_array_equal(np.asarray(labels), np.asarray(out.labels))
        np.testing.assert_allclose(float(inertia), float(out.inertia), rtol=1e-5)

    def test_transform_shape(self, blobs):
        x, _ = blobs
        out = kmeans.fit(KMeansParams(n_clusters=5, seed=0), x)
        t = kmeans.transform(x[:50], out.centroids)
        assert t.shape == (50, 5)
        np.testing.assert_array_equal(np.asarray(t).argmin(1), np.asarray(out.labels)[:50])

    def test_random_init(self, blobs):
        x, true_labels = blobs
        # random init is a weaker seeding — it may land in a local optimum,
        # so only require a decent partition across restarts
        out = kmeans.fit(KMeansParams(n_clusters=5, init="random", seed=3, n_init=5), x)
        from sklearn.metrics import adjusted_rand_score

        assert adjusted_rand_score(true_labels, np.asarray(out.labels)) > 0.6

    def test_array_init(self, blobs):
        x, _ = blobs
        init = x[:5].copy()
        out = kmeans.fit(KMeansParams(n_clusters=5, init="array"), x, centroids=init)
        assert float(out.inertia) > 0

    def test_weighted_fit(self, blobs):
        x, _ = blobs
        w = np.ones(len(x), np.float32)
        out = kmeans.fit(KMeansParams(n_clusters=5, seed=0), x, sample_weights=w)
        out_unw = kmeans.fit(KMeansParams(n_clusters=5, seed=0), x)
        np.testing.assert_allclose(
            np.sort(np.asarray(out.centroids), 0),
            np.sort(np.asarray(out_unw.centroids), 0),
            atol=1e-3,
        )

    def test_too_many_clusters_raises(self):
        with pytest.raises(RaftError):
            kmeans.fit(KMeansParams(n_clusters=10), np.zeros((5, 2), np.float32))

    def test_find_k(self):
        x, _ = make_blobs(600, 4, n_clusters=3, cluster_std=0.2, seed=11)
        best_k, scores = kmeans.find_k(np.asarray(x), k_range=[2, 3, 5, 8])
        assert best_k == 3, scores


class TestKMeansBalanced:
    def test_clusters_are_balanced(self):
        x, _ = make_blobs(2000, 8, n_clusters=4, cluster_std=2.0, seed=5)
        centers, labels, sizes = kmeans_balanced.build_clusters(
            KMeansBalancedParams(n_iters=15, seed=2), np.asarray(x), 16
        )
        sizes = np.asarray(sizes)
        assert sizes.sum() == 2000
        assert sizes.min() > 0, sizes  # no empty lists — the IVF requirement
        assert sizes.max() / max(sizes.mean(), 1) < 4.0, sizes

    def test_predict_consistency(self):
        x, _ = make_blobs(500, 6, n_clusters=3, cluster_std=0.3, seed=9)
        x = np.asarray(x)
        centers = kmeans_balanced.fit(KMeansBalancedParams(n_iters=10), x, 8)
        labels = np.asarray(kmeans_balanced.predict(x, centers))
        d = ((x[:, None, :] - np.asarray(centers)[None, :, :]) ** 2).sum(-1)
        np.testing.assert_array_equal(labels, d.argmin(1))

    def test_subsampled_training(self):
        x, _ = make_blobs(3000, 5, n_clusters=4, seed=4)
        params = KMeansBalancedParams(n_iters=10, max_train_points=500)
        centers = kmeans_balanced.fit(params, np.asarray(x), 8)
        assert centers.shape == (8, 5)
        assert np.isfinite(np.asarray(centers)).all()


class TestKMeansBalancedMinibatch:
    """Mini-batch EM (ISSUE 6 tentpole): the rotating-batch trainer must
    preserve the balanced trainer's contract — partition quality and the
    balance property — while the EM loop stops walking the full trainset."""

    def test_params_defaults_drift(self):
        """The r07 drift pin (bench/kmeans_1m.py exercises the new path;
        --full-em is the explicit escape hatch): mini-batch-by-auto IS the
        default, and the build-params threading carries it everywhere."""
        p = KMeansBalancedParams()
        assert p.train_mode == "auto"
        assert p.batch_rows == 65536
        assert p.n_iters == 20 and p.small_ratio == 0.25
        from raft_tpu.neighbors import cagra, ivf_flat, ivf_pq

        for ip in (ivf_flat.IndexParams(), ivf_pq.IndexParams()):
            assert ip.kmeans_train_mode == "auto"
            assert ip.kmeans_batch_rows == 65536
        cp = cagra.IndexParams()
        assert cp.build_kmeans_train_mode == "auto"
        assert cp.build_kmeans_batch_rows == 65536
        # plain-Lloyd KMeansParams keeps full EM by default (tol-based
        # convergence is its contract); the knob exists for parallel.kmeans
        assert KMeansParams().train_mode == "full"

    def test_auto_resolution_rule(self):
        from raft_tpu.cluster.kmeans_balanced import resolve_train_mode

        assert resolve_train_mode("auto", 2 * 65536, 65536) == "full"
        assert resolve_train_mode("auto", 2 * 65536 + 1, 65536) == "minibatch"
        assert resolve_train_mode("full", 10**9, 64) == "full"
        assert resolve_train_mode("minibatch", 10, 64) == "minibatch"
        with pytest.raises(RaftError):
            resolve_train_mode("bogus", 100, 64)

    def test_auto_below_threshold_is_bitwise_full(self):
        x, _ = make_blobs(1200, 6, n_clusters=4, cluster_std=0.5, seed=3)
        x = np.asarray(x)
        a = kmeans_balanced.fit(
            KMeansBalancedParams(n_iters=8, seed=1, train_mode="auto"), x, 8)
        f = kmeans_balanced.fit(
            KMeansBalancedParams(n_iters=8, seed=1, train_mode="full"), x, 8)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(f))

    def test_minibatch_quality_parity(self):
        """Partition quality: mini-batch centers' clustering cost within a
        few percent of full EM's on clustered data."""
        from raft_tpu.cluster.kmeans import cluster_cost

        x, _ = make_blobs(6000, 8, n_clusters=12, cluster_std=0.8, seed=11)
        x = np.asarray(x)
        full = kmeans_balanced.fit(
            KMeansBalancedParams(n_iters=15, seed=2, train_mode="full"),
            x, 24)
        mb = kmeans_balanced.fit(
            KMeansBalancedParams(n_iters=15, seed=2, train_mode="minibatch",
                                 batch_rows=1024), x, 24)
        c_full = float(cluster_cost(x, full))
        c_mb = float(cluster_cost(x, mb))
        assert c_mb < 1.10 * c_full, (c_mb, c_full)

    def test_minibatch_balance_cap_property(self):
        """The balance property (no empty lists, bounded skew — the IVF
        requirement the balancing re-seed exists for) holds under
        mini-batch EM with per-batch counts."""
        x, _ = make_blobs(4000, 8, n_clusters=4, cluster_std=2.0, seed=5)
        centers, labels, sizes = kmeans_balanced.build_clusters(
            KMeansBalancedParams(n_iters=15, seed=2, train_mode="minibatch",
                                 batch_rows=512), np.asarray(x), 16)
        sizes = np.asarray(sizes)
        assert sizes.sum() == 4000
        assert sizes.min() > 0, sizes  # no empty lists — the IVF requirement
        assert sizes.max() / max(sizes.mean(), 1) < 4.0, sizes

    def test_minibatch_subsample_composes(self):
        """max_train_points (the IVF trainset fraction) and mini-batch EM
        compose: the batch rotates over the subsample."""
        x, _ = make_blobs(3000, 5, n_clusters=4, seed=4)
        params = KMeansBalancedParams(n_iters=10, max_train_points=1000,
                                      train_mode="minibatch", batch_rows=256)
        centers = kmeans_balanced.fit(params, np.asarray(x), 8)
        assert centers.shape == (8, 5)
        assert np.isfinite(np.asarray(centers)).all()


@pytest.mark.slow
def test_minibatch_em_1m_quality_and_auto():
    """Heavy 1M case (slow manifest, ISSUE 6): at 1M the auto default IS
    mini-batch (trainset > 2 x 65536), and its partition cost stays within
    10% of full EM while touching ~1/8 of the rows per iteration."""
    from raft_tpu.cluster.kmeans import cluster_cost
    from raft_tpu.cluster.kmeans_balanced import resolve_train_mode

    n, d, k = 1_000_000, 16, 128
    x, _ = make_blobs(n, d, n_clusters=k, cluster_std=1.0, seed=1)
    x = np.asarray(x)
    assert resolve_train_mode("auto", n, 65536) == "minibatch"
    mb = kmeans_balanced.fit(
        KMeansBalancedParams(n_iters=10, seed=0, train_mode="auto"), x, k)
    full = kmeans_balanced.fit(
        KMeansBalancedParams(n_iters=10, seed=0, train_mode="full"), x, k)
    c_mb = float(cluster_cost(x, mb))
    c_full = float(cluster_cost(x, full))
    assert c_mb < 1.10 * c_full, (c_mb, c_full)
