"""Elastic live resharding tests (tier-1 ``stream`` marker, ISSUE 13).

The acceptance spine: a power-of-two split/merge is an ONLINE topology
change — results before and after the flip are identical to a fresh build
over exactly the live rows (the split locality rule moves every id to a
deterministic successor, so nothing can be lost or duplicated), writes
landing mid-migration carry over at the atomic swap, a replica killed or
staled mid-split never fails a query, and a :class:`SimulatedCrash` at any
of the three reshard fault points recovers — manifest + per-shard WAL
replay — to a state id-for-id equal to an uncrashed twin. Deterministic by
construction: injected clocks, fault callbacks instead of timing races,
no wall-clock sleeps in assertions.
"""

import os
import threading

import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu import stream
from raft_tpu.core.errors import RaftError
from raft_tpu.neighbors import brute_force
from raft_tpu.serve import SearchService
from raft_tpu.testing import faults

pytestmark = pytest.mark.stream


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


@pytest.fixture
def data(rng):
    return rng.standard_normal((280, 16)).astype(np.float32)


@pytest.fixture
def queries(rng):
    return rng.standard_normal((5, 16)).astype(np.float32)


def bf_build(x):
    return brute_force.BruteForce().build(jnp.asarray(x))


def sharded_bf(data, n_shards, **kw):
    return stream.ShardedMutableIndex(data, n_shards=n_shards,
                                      build=bf_build, **kw)


def bf_gids(live_mat, live_gids, queries, k):
    _, pos = brute_force.knn(jnp.asarray(live_mat), jnp.asarray(queries), k)
    pos = np.asarray(pos)
    return np.where(pos >= 0, np.asarray(live_gids)[np.clip(pos, 0, None)], -1)


# -- the parity spine ---------------------------------------------------------

def test_split_and_merge_parity_vs_fresh_build(data, queries, rng):
    """Split 2→4 then merge 4→2 after a write script: every topology's
    results are bit-equal to a fresh brute-force build over exactly the
    live rows — AND to a mesh CONSTRUCTED at the target topology — so a
    reshard is observationally a no-op for readers."""
    sm = sharded_bf(data, 2, delta_capacity=64)
    ins = rng.standard_normal((14, 16)).astype(np.float32)
    gids = sm.upsert(ins)
    dele = [3, 17, 101, int(gids[4])]
    assert sm.delete(dele) == 4
    live_mask = np.ones(len(data), bool)
    live_mask[[3, 17, 101]] = False
    ins_mask = np.ones(14, bool)
    ins_mask[4] = False
    live_mat = np.concatenate([data[live_mask], ins[ins_mask]])
    live_g = np.concatenate([np.nonzero(live_mask)[0],
                             np.asarray(gids)[ins_mask]])
    want = bf_gids(live_mat, live_g, queries, 10)

    rep = sm.reshard(4, warm_buckets=(5,))
    assert sm.n_shards == 4 and rep["to"] == 4
    assert rep["rows_moved"] == len(live_g)
    _, got = sm.search(queries, 10)
    np.testing.assert_array_equal(np.asarray(got), want)
    # the split locality rule: shard s's ids land on s or s+S only
    for s, sh in enumerate(sm.shards):
        st = sh._state
        lives = np.concatenate([st.id_map[st.sealed_alive],
                                st.delta_ids[:st.delta_n][
                                    st.delta_alive[:st.delta_n]]])
        assert set(np.asarray(stream.shard_of(lives, 4))) <= {s}, s

    # merge back: same results, aggregate size preserved
    sm.reshard(2)
    assert sm.n_shards == 2
    _, got2 = sm.search(queries, 10)
    np.testing.assert_array_equal(np.asarray(got2), want)
    assert sm.size == len(live_g)

    # multi-step jump (2 → 8 runs as two committed doublings)
    rep = sm.reshard(8)
    assert sm.n_shards == 8 and len(rep["steps"]) == 2
    _, got3 = sm.search(queries, 10)
    np.testing.assert_array_equal(np.asarray(got3), want)


def test_reshard_validations(data, tmp_path):
    sm = sharded_bf(data, 2, delta_capacity=32)
    with pytest.raises(RaftError, match="power-of-two"):
        sm.reshard(3)
    with pytest.raises(RaftError, match="already at"):
        sm.reshard(2)
    with pytest.raises(RaftError, match="n_shards"):
        sm.reshard(0)
    with pytest.raises(RaftError, match="published name"):
        sm.reshard(4, publisher=SearchService(start_workers=False))
    # no retained store: the fold has nothing to rebuild from
    bare = sharded_bf(data, 2, delta_capacity=32, retain_vectors=False)
    with pytest.raises(RaftError, match="retained row store"):
        bare.reshard(4)
    # a split that would leave an empty successor refuses whole (nothing
    # flipped, the donor mesh still serves)
    tiny = sharded_bf(data[:6], 2, delta_capacity=32)
    with pytest.raises(RaftError, match="no live rows|no rows"):
        tiny.reshard(32)
    assert tiny.n_shards == 2 and tiny.size == 6
    # a loaded mesh without build= cannot reshard (but says why)
    sm2 = sharded_bf(data, 2, delta_capacity=32, wal_dir=str(tmp_path))
    del sm2
    rec = stream.ShardedMutableIndex.load(str(tmp_path))
    with pytest.raises(RaftError, match="build recipe"):
        rec.reshard(4)


def test_mid_migration_writes_carry_over(data, queries):
    """Writes landing on an ALREADY-FOLDED donor mid-migration (injected
    deterministically from the reshard/split fault callback, so no timing
    race) carry over at the swap: upserts visible, deletes honored, the
    same contract as compaction's mid-fold writes."""
    sm = sharded_bf(data, 2, delta_capacity=64)
    probe = np.full((2, 16), 7.5, np.float32)
    mid = {}

    def midwrite(ctx):
        # fires as donor 1's fold STARTS — donor 0 is already folded, so
        # writes homed there can only survive via the carry-over
        mid["g"] = sm.upsert(probe, ids=[2000, 2001])
        sm.delete([11])

    with faults.scope():
        faults.inject("reshard/split", callback=midwrite, after=1, times=1)
        rep = sm.reshard(4)
    assert rep["steps"][0]["carried_over"] >= 1
    _, ids = sm.search(probe[:1], 4)
    got = set(np.asarray(ids)[0].tolist())
    assert {2000, 2001} <= got, got
    assert sm.delete([11]) == 0  # the mid-migration delete was honored
    # full parity against the live-row ground truth
    live_mask = np.ones(len(data), bool)
    live_mask[11] = False
    live_mat = np.concatenate([data[live_mask], probe])
    live_g = np.concatenate([np.nonzero(live_mask)[0], [2000, 2001]])
    want = bf_gids(live_mat, live_g, queries, 10)
    _, got = sm.search(queries, 10)
    np.testing.assert_array_equal(np.asarray(got), want)


def test_reshard_under_load_loses_nothing(data):
    """Readers and writers live on the service while the topology doubles:
    zero failed queries, zero lost writes, and the post-flip mesh serves
    every id the old one did plus everything written mid-migration."""
    sm = sharded_bf(data, 2, delta_capacity=256, name="live")
    svc = SearchService(max_batch=8, max_wait_us=200.0, max_queue_rows=512)
    svc.publish("live", sm, k=5)
    sm.warm(svc.buckets, ks=(5,))
    errors, done = [], []
    lock = threading.Lock()
    stop = threading.Event()

    def reader(tid):
        j = 0
        while not stop.is_set() or j < 25:
            if j >= 25 and stop.is_set():
                break
            try:
                _, ids = svc.search("live", data[(tid * 37 + j) % 200:
                                                 (tid * 37 + j) % 200 + 1], 5)
                with lock:
                    done.append(int(np.asarray(ids)[0, 0]))
            except Exception as e:
                with lock:
                    errors.append(repr(e))
            j += 1

    threads = [threading.Thread(target=reader, args=(t,)) for t in range(3)]
    for t in threads:
        t.start()
    for step in range(8):
        svc.upsert("live", data[step:step + 2] + 0.5, ids=[900 + 2 * step,
                                                           901 + 2 * step])
    rep = sm.reshard(4, publisher=svc, name="live", ks=(5,))
    for step in range(8, 12):
        svc.upsert("live", data[step:step + 2] + 0.5, ids=[900 + 2 * step,
                                                           901 + 2 * step])
    stop.set()
    for t in threads:
        t.join(60)
        assert not t.is_alive(), "reader wedged"
    svc.shutdown()
    assert errors == []
    assert len(done) >= 75
    assert sm.n_shards == 4 and rep["steps"][0]["publish"]["version"] == 2
    # every write (pre-, mid- and post-flip) is live exactly once
    assert sm.size == len(data) + 24
    for gid in range(900, 924):
        row = (gid - 900) // 2 + (gid - 900) % 2
        _, ids = sm.search(data[row:row + 1] + 0.5, 4)
        assert gid in set(np.asarray(ids)[0].tolist()), gid


# -- replicated split ---------------------------------------------------------

def test_replicated_split_twins_in_lockstep_fenced_twin_excluded(data):
    """Splitting a replicated mesh rebuilds R fresh twins per successor in
    lockstep, sourced from a LIVE twin: a stale (write-fenced) twin's
    divergence is excluded — the write it missed is present after the
    split — and the successor groups come up fully healthy (the reshard
    re-replicates, healing staleness)."""
    sm = stream.ShardedMutableIndex(
        data, n_shards=2, replicas=2, build=bf_build, delta_capacity=64,
        name="rs")
    probe = np.full((1, 16), 3.3, np.float32)
    with faults.scope():
        # one twin of shard 0 misses an acknowledged write -> stale
        faults.inject("replica/upsert", RuntimeError("device fault"),
                      match=lambda c: c["replica"] == "rs/shard0/r1",
                      times=1)
        sm.upsert(probe, ids=[5000])
    assert sm.stats()["stale"] == 1
    sm.reshard(4)
    st = sm.stats()
    assert st["shards"] == 4 and st["replicas"] == 8
    assert st["stale"] == 0 and st["healthy"] == 2, st
    for sh in sm.shards:
        assert isinstance(sh, stream.ReplicatedShard)
        assert sh.n_replicas == 2
        # lockstep: both twins answer identically
        d0, i0 = sh.replicas[0].search(probe, 3)
        d1, i1 = sh.replicas[1].search(probe, 3)
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
    _, ids = sm.search(probe, 3)
    assert 5000 in set(np.asarray(ids)[0].tolist())


def test_replica_killed_mid_split_never_fails_a_query(data):
    """A replica killed while the migration runs (fault injected from the
    reshard/split callback — deterministically mid-migration): reads keep
    failing over to the surviving twin, the reshard completes, zero
    queries fail."""
    sm = stream.ShardedMutableIndex(
        data, n_shards=2, replicas=2, build=bf_build, delta_capacity=64,
        fencing=stream.FencingPolicy(max_consecutive=1, backoff_s=1e9),
        name="kz")
    outcomes = []

    def kill_and_read(ctx):
        faults.inject("replica/search", faults.FaultError("killed"),
                      match=lambda c: c["replica"].startswith("kz/shard0/r0"))
        # reads mid-migration route through the failover pick
        for lo in (0, 40):
            d, i = sm.search(data[lo:lo + 2], 5)
            outcomes.append(np.asarray(i).shape)

    with faults.scope():
        faults.inject("reshard/split", callback=kill_and_read, times=1)
        sm.reshard(4)
    assert outcomes == [(2, 5), (2, 5)]
    assert sm.n_shards == 4
    d, i = sm.search(data[:3], 5)  # post-flip serving intact
    assert np.asarray(i).shape == (3, 5)


# -- crash recovery -----------------------------------------------------------

def _write_script(sm, seed=9):
    r = np.random.default_rng(seed)
    g = sm.upsert(r.standard_normal((10, 16)).astype(np.float32),
                  ids=np.arange(1000, 1010))
    sm.delete([5, 7, 1003])
    return g


def test_kill_mid_reshard_recovers_at_every_fault_point(data, queries,
                                                        tmp_path):
    """THE acceptance bit: a SimulatedCrash at each of reshard/split,
    reshard/flip and reshard/manifest recovers — manifest + per-shard WAL
    replay — to the OLD topology with id-for-id parity against an
    uncrashed twin that never resharded: no acknowledged write lost, no
    write resurrected (the aborted successors' files are ignored)."""
    for point in ("reshard/split", "reshard/flip", "reshard/manifest"):
        d = str(tmp_path / point.replace("/", "_"))
        sm = sharded_bf(data, 2, delta_capacity=64, wal_dir=d)
        _write_script(sm)
        with faults.scope():
            faults.inject(point, faults.SimulatedCrash("kill -9"))
            with pytest.raises(faults.SimulatedCrash):
                sm.reshard(4)
        del sm  # the process is gone; the directory is all that survives
        rec = stream.ShardedMutableIndex.load(d, build=bf_build)
        assert rec.n_shards == 2, point
        twin = sharded_bf(data, 2, delta_capacity=64)
        _write_script(twin)
        dt, it = twin.search(queries, 10)
        dr, ir = rec.search(queries, 10)
        np.testing.assert_array_equal(np.asarray(it), np.asarray(ir), point)
        assert rec.size == twin.size
        assert rec.last_recovery["replayed"] > 0, point


def test_committed_reshard_recovers_to_the_new_topology(data, queries,
                                                        tmp_path):
    """Past the manifest rename the reshard is durable: a crash AFTER the
    commit point recovers to the new topology — with the carry-over
    writes that only ever hit the successor WALs."""
    d = str(tmp_path / "committed")
    sm = sharded_bf(data, 2, delta_capacity=64, wal_dir=d)
    _write_script(sm)

    def midwrite(ctx):  # a write only the successor WALs will hold
        sm.upsert(np.full((1, 16), 9.25, np.float32), ids=[7000])

    with faults.scope():
        faults.inject("reshard/split", callback=midwrite, after=1, times=1)
        sm.reshard(4)
    post_flip = sm.upsert(np.full((1, 16), -9.25, np.float32), ids=[7001])
    dt, it = sm.search(queries, 10)
    del sm
    rec = stream.ShardedMutableIndex.load(d, build=bf_build)
    assert rec.n_shards == 4
    assert rec.last_recovery["topology_epoch"] == 1
    dr, ir = rec.search(queries, 10)
    np.testing.assert_array_equal(np.asarray(it), np.asarray(ir))
    for gid, val in ((7000, 9.25), (int(post_flip[0]), -9.25)):
        _, ids = rec.search(np.full((1, 16), val, np.float32), 3)
        assert gid in set(np.asarray(ids)[0].tolist()), gid


def test_mesh_save_load_and_crash_mid_save(data, queries, tmp_path):
    """Atomic mesh snapshots (satellite): save() routes every per-shard
    snapshot AND the manifest through atomic_write; a crash mid-save — on
    a shard snapshot or on the manifest itself — leaves the previous
    manifest+snapshot set loadable with zero acknowledged-write loss."""
    d = str(tmp_path / "mesh")
    sm = sharded_bf(data, 2, delta_capacity=64, wal_dir=d)
    _write_script(sm)
    want_d, want_i = sm.search(queries, 10)

    # crash on shard 1's snapshot rename: shard 0 already saved (its pair
    # is consistent on its own), manifest still the old one -> loadable
    with faults.scope():
        faults.inject("serialize/atomic-write",
                      faults.SimulatedCrash("kill -9"),
                      match=lambda c: "shard1" in c["path"])
        with pytest.raises(faults.SimulatedCrash):
            sm.save()
    rec = stream.ShardedMutableIndex.load(d, build=bf_build)
    _, ir = rec.search(queries, 10)
    np.testing.assert_array_equal(np.asarray(want_i), np.asarray(ir))

    # crash on the manifest rename: every shard snapshot is new, manifest
    # old — per-shard wal_seq stamps keep each pair consistent
    with faults.scope():
        faults.inject("serialize/atomic-write",
                      faults.SimulatedCrash("kill -9"),
                      match=lambda c: c["path"].endswith("manifest"))
        with pytest.raises(faults.SimulatedCrash):
            sm.save()
    rec = stream.ShardedMutableIndex.load(d, build=bf_build)
    _, ir = rec.search(queries, 10)
    np.testing.assert_array_equal(np.asarray(want_i), np.asarray(ir))

    # clean save + snapshot-only save/load without durability armed
    sm.save()
    rec = stream.ShardedMutableIndex.load(d, build=bf_build)
    assert rec.last_recovery["replayed"] == 0  # snapshots cover the log
    plain = sharded_bf(data, 2, delta_capacity=64)
    _write_script(plain)
    d2 = str(tmp_path / "snaponly")
    plain.save(d2)
    rec2 = stream.ShardedMutableIndex.load(d2)
    assert rec2._wal_dir is None
    _, ir2 = rec2.search(queries, 10)
    np.testing.assert_array_equal(np.asarray(want_i), np.asarray(ir2))


def test_wal_dir_refuses_an_earlier_meshes_directory(data, tmp_path):
    """Constructing a fresh mesh over a wal_dir holding a committed
    manifest must refuse — a fresh epoch-0 manifest would shadow every
    acknowledged write of the earlier life, and a RESHARDED earlier life
    keeps its files under a different epoch that the per-shard WAL probe
    would never even see."""
    d = str(tmp_path / "life1")
    sm = sharded_bf(data, 2, delta_capacity=64, wal_dir=d)
    _write_script(sm)
    del sm
    with pytest.raises(RaftError, match="already holds a mesh manifest"):
        sharded_bf(data, 2, delta_capacity=64, wal_dir=d)
    # the epoch>=1 case (the files live at e1 names, so only the manifest
    # check can catch it): recover, reshard, and try to re-construct
    rec = stream.ShardedMutableIndex.load(d, build=bf_build)
    rec.reshard(4)
    del rec
    with pytest.raises(RaftError, match="already holds a mesh manifest"):
        sharded_bf(data, 2, delta_capacity=64, wal_dir=d)
    # the refused constructions shadowed nothing: the resharded mesh loads
    back = stream.ShardedMutableIndex.load(d, build=bf_build)
    assert back.n_shards == 4


def test_replicated_primary_goes_stale_mid_migration_nothing_lost(data):
    """A replicated donor's PRIMARY twin goes stale mid-migration (a
    write raises past admission on it): later acknowledged group writes
    skip the stale twin, so the commit must read carry-over from a twin
    that received them — the fold-time primary would silently drop
    every write since the staleness event."""
    sm = stream.ShardedMutableIndex(
        data, n_shards=2, replicas=2, build=bf_build, delta_capacity=64,
        name="sg")
    cand = np.arange(10_000, 40_000)
    to0 = cand[stream.shard_of(cand, 2) == 0]

    def midwrite(ctx):
        # fires after donor 0's fold: these writes home on (already
        # folded) shard 0 and can only survive via carry-over. The FIRST
        # write stales r0 — the twin the fold snapshotted — so the
        # second lands only on r1.
        faults.inject("replica/upsert", RuntimeError("dev fault"),
                      match=lambda c: c["replica"] == "sg/shard0/r0",
                      times=1)
        sm.upsert(np.full((1, 16), 4.5, np.float32), ids=[int(to0[0])])
        sm.upsert(np.full((1, 16), -4.5, np.float32), ids=[int(to0[1])])

    with faults.scope():
        faults.inject("reshard/split", callback=midwrite, after=1, times=1)
        sm.reshard(4)
    for gid, val in ((int(to0[0]), 4.5), (int(to0[1]), -4.5)):
        _, ids = sm.search(np.full((1, 16), val, np.float32), 3)
        assert gid in set(np.asarray(ids)[0].tolist()), (gid, ids)


def test_manifest_write_failure_rolls_the_flip_back(data, queries,
                                                    tmp_path):
    """A manifest that fails to LAND (an OSError, not a crash) must not
    leave the mesh flipped in memory while the durable manifest names the
    old topology — reshard() rolls the swap back (donors untouched, still
    logging) and a retry commits cleanly."""
    d = str(tmp_path / "roll")
    sm = sharded_bf(data, 2, delta_capacity=64, wal_dir=d)
    _write_script(sm)
    want_d, want_i = sm.search(queries, 10)
    with faults.scope():
        faults.inject("serialize/atomic-write", OSError("disk full"),
                      match=lambda c: c["path"].endswith("manifest"))
        with pytest.raises(OSError, match="disk full"):
            sm.reshard(4)
    assert sm.n_shards == 2  # the swap rolled back
    _, ir = sm.search(queries, 10)
    np.testing.assert_array_equal(np.asarray(want_i), np.asarray(ir))
    g = sm.upsert(np.full((1, 16), 6.5, np.float32))  # writes still land
    rep = sm.reshard(4)  # the retry reuses the epoch and commits
    assert sm.n_shards == 4 and rep["epoch"] == 1
    _, ids = sm.search(np.full((1, 16), 6.5, np.float32), 3)
    assert int(g[0]) in set(np.asarray(ids)[0].tolist())
    rec = stream.ShardedMutableIndex.load(d, build=bf_build)
    assert rec.n_shards == 4
    _, ids = rec.search(np.full((1, 16), 6.5, np.float32), 3)
    assert int(g[0]) in set(np.asarray(ids)[0].tolist())


def test_per_shard_wal_attribution_and_sawtooth(data, tmp_path):
    """Satellite: per-shard WAL metrics report under name/shard<i>, and
    truncation saw-tooths with each shard's OWN compaction fold — one
    shard's fold resets its log while its sibling's keeps its records."""
    from raft_tpu.obs import metrics

    d = str(tmp_path / "saw")
    sm = sharded_bf(data, 2, delta_capacity=16, wal_dir=d, name="saw")
    cand = np.arange(10_000, 40_000)
    homes = stream.shard_of(cand, 2)
    to0, to1 = cand[homes == 0], cand[homes == 1]
    sm.upsert(np.zeros((6, 16), np.float32), ids=to0[:6])
    sm.upsert(np.ones((3, 16), np.float32), ids=to1[:3])
    snap = metrics.to_json()
    assert snap.get('raft_tpu_wal_appends_total{name="saw/shard0"}') >= 1
    assert snap.get('raft_tpu_wal_appends_total{name="saw/shard1"}') >= 1
    w0, w1 = sm.shards[0]._wal, sm.shards[1]._wal
    assert w0.size_bytes > 0 and w1.size_bytes > 0
    rep = sm.compact(shard=0)  # the fold snapshots + truncates shard 0 only
    assert rep["snapshot"].endswith("shard0.e0.idx")
    assert w0.size_bytes == 0 and w1.size_bytes > 0
    # the truncated shard recovers from its fresh snapshot, the other
    # from snapshot + replay — the mesh as a whole loses nothing
    want_d, want_i = sm.search(data[:4], 10)
    del sm
    rec = stream.ShardedMutableIndex.load(d)
    _, ir = rec.search(data[:4], 10)
    np.testing.assert_array_equal(np.asarray(want_i), np.asarray(ir))


# -- warm / compile discipline ------------------------------------------------

def test_save_serializes_with_a_live_reshard(data, tmp_path):
    """save() must not interleave with a reshard commit (which closes
    donor WALs and flips the epoch under it): a save launched
    mid-migration blocks on the topology lock and lands AFTER the flip,
    writing one consistent post-flip set."""
    d = str(tmp_path / "ser")
    sm = sharded_bf(data, 2, delta_capacity=64, wal_dir=d)
    _write_script(sm)
    box = {}

    def midsave(ctx):
        t = threading.Thread(
            target=lambda: box.setdefault("ok", (sm.save(), True)[1]))
        t.start()
        box["t"] = t

    with faults.scope():
        faults.inject("reshard/split", callback=midsave, after=1, times=1)
        sm.reshard(4)
    box["t"].join(60)
    assert not box["t"].is_alive() and box.get("ok")
    rec = stream.ShardedMutableIndex.load(d, build=bf_build)
    assert rec.n_shards == 4  # the save saw the committed topology, whole
    assert rec.last_recovery["topology_epoch"] == 1


def test_zero_cold_compile_warm_ladder_across_the_flip(data, queries):
    """The zero-cold-compile discipline survives a topology change: after
    the rehearsal run (which compiles both topologies' program sets), an
    identical publish → serve → reshard → serve schedule triggers ZERO
    compiles — the successors' ladders and the new merge shape were
    warmed through the registry's pre-flip seam, never on the hot path."""
    from raft_tpu.obs import compile as obs_compile

    if not obs_compile.install():  # pragma: no cover - ancient jax
        pytest.skip("jax.monitoring unavailable")
    clock = FakeClock()

    def run(name):
        sm = sharded_bf(data, 2, delta_capacity=16, clock=clock, name=name)
        svc = SearchService(max_batch=4, clock=clock, start_workers=False)
        svc.publish(name, sm, k=5)
        sm.warm(svc.buckets, ks=(5,))
        for step in range(4):
            sm.upsert(data[step:step + 1] + 0.5, ids=[600 + step])
            fut = svc.submit(name, queries[:2], 5)
            clock.advance(1.0)
            svc.pump()
            fut.result(timeout=0)
        sm.reshard(4, publisher=svc, name=name, ks=(5,),
                   warm_buckets=svc.buckets)
        for step in range(4, 8):
            sm.upsert(data[step:step + 1] + 0.5, ids=[600 + step])
            fut = svc.submit(name, queries[:2], 5)
            clock.advance(1.0)
            svc.pump()
            fut.result(timeout=0)
        svc.shutdown()

    run("rehearsal")
    with obs_compile.attribution() as rec:
        run("live")
    assert rec.compile_s == 0.0 and rec.programs == 0


# -- compactor advisory -------------------------------------------------------

def test_compactor_reshard_advised_trigger(data):
    """The reshard_advised watermark: a standing once-per-transition
    advisory (the retune_advised discipline — auto_apply False, the fold
    stays manual), cleared when the topology change lands."""
    from raft_tpu.obs import metrics

    clock = FakeClock()
    sm = sharded_bf(data, 2, delta_capacity=32, clock=clock, name="adv")
    comp = stream.Compactor(
        sm, policy=stream.CompactionPolicy(
            delta_fill=None, tombstone_ratio=None,
            reshard_rows_per_shard=100),
        clock=clock)
    before = metrics.to_json().get(
        'raft_tpu_reshard_advised_total{action="split",name="adv"}', 0)
    assert comp.run_once() is None  # no compaction due; advice still lands
    adv = comp.last_advice
    assert adv is not None and adv["action"] == "split"
    assert adv["target"] == 4 and adv["auto_apply"] is False
    after = metrics.to_json().get(
        'raft_tpu_reshard_advised_total{action="split",name="adv"}', 0)
    assert after == before + 1
    comp.run_once()  # standing advice does NOT re-emit
    assert metrics.to_json().get(
        'raft_tpu_reshard_advised_total{action="split",name="adv"}',
        0) == after
    sm.reshard(4)  # the split relieves the watermark (280/4 = 70 < 100)
    comp.run_once()
    assert comp.last_advice is None
    # a compaction report carries the advisory when one is standing
    comp2 = stream.Compactor(
        sm, policy=stream.CompactionPolicy(
            delta_fill=None, tombstone_ratio=None,
            reshard_rows_per_shard=10),
        clock=clock)
    rep = comp2.run_once(force=True)
    assert rep["reshard_advised"]["action"] == "split"
    # merge-side advisory
    comp3 = stream.Compactor(
        sm, policy=stream.CompactionPolicy(
            delta_fill=None, tombstone_ratio=None,
            reshard_min_rows_per_shard=1000),
        clock=clock)
    comp3.run_once()
    assert comp3.last_advice["action"] == "merge"
    assert comp3.last_advice["target"] == 2
    # an ODD shard count never gets merge advice: reshard() only halves
    # even counts, so the advisory would be permanently unactionable
    odd = sharded_bf(data, 3, delta_capacity=32, clock=clock, name="odd")
    comp4 = stream.Compactor(
        odd, policy=stream.CompactionPolicy(
            delta_fill=None, tombstone_ratio=None,
            reshard_min_rows_per_shard=1000),
        clock=clock)
    comp4.run_once()
    assert comp4.last_advice is None


# -- obs: metrics, ledger, healthz --------------------------------------------

def test_reshard_metrics_ledger_and_health_fold(data):
    """New raft_tpu_reshard_* metrics count the migration, the
    stream_shards gauge transitions at the flip, /healthz folds the
    migration state while it runs, and the donor shards' ledger entries
    retire — the audit proves the split's transient double-buffer frees
    once the donors are released."""
    import gc

    from raft_tpu.obs import mem as obs_mem
    from raft_tpu.obs import metrics

    sm = sharded_bf(data, 2, delta_capacity=32, name="met")
    seen = {}

    def observe(ctx):
        from raft_tpu.obs.http import _fold_replica_health

        seen["health"] = sm.health()["reshard"]
        # the exporter-side fold: migration state rides the /healthz body
        # without degrading the verdict (the old topology keeps serving)
        code, body = _fold_replica_health(
            200, {"status": "ready"}, sm.health())
        seen["fold"] = (code, body.get("status"), body.get("reshard"))
        seen["gauge_mid"] = metrics.to_json().get(
            'raft_tpu_stream_shards{name="met"}')

    assert sm.health()["reshard"] is None
    with faults.scope():
        faults.inject("reshard/split", callback=observe, after=1, times=1)
        sm.reshard(4)
    # mid-migration: health folds the migration, the gauge still reports
    # the serving (old) topology
    assert seen["health"]["action"] == "split"
    assert seen["health"]["from"] == 2 and seen["health"]["to"] == 4
    assert seen["health"]["folded_donors"] == 1
    code, verdict, fold = seen["fold"]
    assert code == 200 and verdict == "ready"
    assert fold["action"] == "split" and fold["to"] == 4
    assert seen["gauge_mid"] == 2
    snap = metrics.to_json()
    assert snap.get('raft_tpu_stream_shards{name="met"}') == 4
    assert snap.get('raft_tpu_reshard_migrations_total'
                    '{action="split",name="met",phase="started"}') == 1
    assert snap.get('raft_tpu_reshard_migrations_total'
                    '{action="split",name="met",phase="completed"}') == 1
    assert snap.get(
        'raft_tpu_reshard_rows_moved_total{name="met"}') == len(data)
    assert any(k.startswith("raft_tpu_reshard_seconds") for k in snap)
    assert sm.health()["reshard"] is None  # cleared at the commit
    # donor retirement: with no leases pinning the old topology, the
    # retired entries collect and the audit comes back clean
    gc.collect()
    aud = obs_mem.audit(collect=True)
    leaks = [r for r in aud["retired_unfreed"]
             if r["name"].startswith("met/")]
    assert leaks == [], leaks
