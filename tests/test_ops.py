"""Pallas kernel tests (raft_tpu.ops) — run through the Pallas interpreter on
the CPU mesh; the same code lowers to Mosaic on TPU (verified on hardware,
see ops/topk.py benchmark notes)."""

import numpy as np
import pytest

from raft_tpu.ops import topk_pallas


@pytest.mark.parametrize("m,n,k", [(8, 256, 4), (16, 1000, 10), (9, 130, 64)])
def test_topk_pallas_matches_lax(rng, m, n, k):
    import jax.numpy as jnp
    from jax import lax

    if k > n:
        pytest.skip("k > n")
    x = jnp.asarray(rng.random((m, n)).astype(np.float32))
    v, i = topk_pallas(x, k, select_min=True, blk=256)
    v0, _ = lax.top_k(-x, k)
    np.testing.assert_allclose(np.asarray(v), np.asarray(-v0), atol=0)
    gathered = np.take_along_axis(np.asarray(x), np.asarray(i), axis=1)
    np.testing.assert_allclose(gathered, np.asarray(v), atol=0)


def test_topk_pallas_select_max(rng):
    import jax.numpy as jnp
    from jax import lax

    x = jnp.asarray(rng.random((5, 300)).astype(np.float32))
    v, i = topk_pallas(x, 7, select_min=False, blk=128)
    v0, _ = lax.top_k(x, 7)
    np.testing.assert_allclose(np.asarray(v), np.asarray(v0), atol=0)


def test_topk_pallas_k_too_big(rng):
    import jax.numpy as jnp

    x = jnp.zeros((4, 300), jnp.float32)
    with pytest.raises(ValueError):
        topk_pallas(x, 257)


@pytest.mark.parametrize("m,n,k", [(4, 2000, 65), (8, 1500, 128),
                                   (4, 3000, 193), (4, 1000, 256)])
def test_topk_pallas_wide_k(rng, m, n, k):
    """64 < k <= 256 routes through the bitonic-merge running buffer
    (VERDICT r4 #5); same exactness + tie contract as lax.top_k."""
    import jax.numpy as jnp
    from jax import lax

    x = jnp.asarray(rng.random((m, n)).astype(np.float32))
    v, i = topk_pallas(x, k, select_min=True, blk=256)
    v0, i0 = lax.top_k(-x, k)
    np.testing.assert_allclose(np.asarray(v), np.asarray(-v0), atol=0)
    np.testing.assert_array_equal(np.asarray(i), np.asarray(i0))


def test_topk_pallas_wide_k_ties(rng):
    """Duplicate values across blocks: ties must resolve to the lowest
    column id, matching lax.top_k, through the bitonic merge."""
    import jax.numpy as jnp
    from jax import lax

    x = rng.integers(0, 12, (6, 2000)).astype(np.float32)  # heavy ties
    xj = jnp.asarray(x)
    v, i = topk_pallas(xj, 100, select_min=True, blk=256)
    v0, i0 = lax.top_k(-xj, 100)
    np.testing.assert_allclose(np.asarray(v), np.asarray(-v0), atol=0)
    np.testing.assert_array_equal(np.asarray(i), np.asarray(i0))


def test_topk_pallas_inf_inputs(rng):
    """Masked +inf entries (select_min) must lose to every finite entry but
    still be picked — with their real column ids — when a row has fewer than
    k finite values (the knn_merge_parts masked-slot pattern)."""
    import jax.numpy as jnp
    from jax import lax

    x = rng.random((6, 300)).astype(np.float32)
    x[0, 5:] = np.inf          # row 0: only 5 finite entries
    x[1, ::2] = np.inf
    xj = jnp.asarray(x)
    v, i = topk_pallas(xj, 8, select_min=True, blk=128)
    v0, i0 = lax.top_k(-xj, 8)
    np.testing.assert_allclose(np.asarray(v), np.asarray(-v0))
    # row 0 slots 5..7 are +inf but must carry REAL in-range column ids
    assert np.isinf(np.asarray(v)[0, 5:]).all()
    assert (np.asarray(i) >= 0).all() and (np.asarray(i) < 300).all()


@pytest.mark.slow
@pytest.mark.parametrize("wide_merge", ["half", "concat"])
def test_topk_pallas_wide_merge_forms_agree(rng, wide_merge):
    """Both wide-merge formulations — "half" (r06, every intermediate <= kh
    lanes) and "concat" (r05, kept for the chaining repro/bisect) — are the
    same network restricted to the kept half, so both must be bitwise
    lax.top_k."""
    import jax.numpy as jnp
    from jax import lax

    x = jnp.asarray(rng.random((4, 1500)).astype(np.float32))
    v, i = topk_pallas(x, 193, select_min=True, blk=256,
                       wide_merge=wide_merge)
    v0, i0 = lax.top_k(-x, 193)
    np.testing.assert_allclose(np.asarray(v), np.asarray(-v0), atol=0)
    np.testing.assert_array_equal(np.asarray(i), np.asarray(i0))


@pytest.mark.slow
def test_topk_pallas_two_wide_instances(rng):
    """The kh=256 chaining repro (VERDICT r5 #3), committed as a test: TWO
    wide-k (k > 128) kernel instances chained inside ONE jit program — the
    per-chunk + final-merge composition of ivf_pq's scan at the CAGRA
    build-chunk k = gpu_top_k + 1 = 193. The r05 toolchain failed to compile
    this on TPU (the 2*kh = 512-lane merge intermediates; BASELINE.md
    "Round-5 wide-k selector study"); the r06 half-width merge caps every
    intermediate at kh lanes, and this test pins the composition so the
    select_k dispatch lift can never silently outlive a regression — on TPU
    it exercises the real Mosaic compile, on CPU the interpreter (numerics
    only). Shapes are the build chunk's scaled down ~16x (same kh, same
    two-instance structure)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    k = 193
    x = jnp.asarray(rng.random((8, 1024)).astype(np.float32))

    @jax.jit
    def two_instance(x):
        v1, i1 = topk_pallas(x, k, blk=512)
        pool = jnp.tile(v1, (1, 4))                     # (m, 4k) final merge
        v2, i2 = topk_pallas(pool, k, blk=512)
        return v1, i1, v2, i2

    v1, i1, v2, i2 = two_instance(x)
    v0, i0 = lax.top_k(-x, k)
    np.testing.assert_allclose(np.asarray(v1), np.asarray(-v0), atol=0)
    np.testing.assert_array_equal(np.asarray(i1), np.asarray(i0))
    # the second instance re-selects over four copies of the sorted top-k:
    # its values are the first k of the ascending tile
    np.testing.assert_allclose(np.asarray(v2),
                               np.kron(np.asarray(v1)[:, :(k + 3) // 4 + 1],
                                       np.ones(4))[:, :k], atol=0)
